"""A/B the paged-decode kernels on the real chip: GB/s vs the roofline.

Round-3 measured the vector-formulated kernels at ~85-90 GB/s (11% of
the v5e 819 GB/s HBM roofline) and isolated the bound to the per-page
VPU math (PERF.md "Paged decode kernel"). This measures the round-4
MXU-formulated kernel (block-diagonal dots, d-major k pages) against it
at serving shapes. Bandwidth accounting = bytes of k/v actually read
(pages covering seq_len) / device time per call.

    python tools/bench_paged_decode.py            # 1B-class MHA shapes
    python tools/bench_paged_decode.py --gqa      # bench-1B GQA shapes
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def bench(fn, args, short=512, long=2048):
    """Per-call DEVICE time via the delta of two loop lengths — host
    wall time over the remote-device tunnel is dispatch-dominated
    (~60-100 ms/launch), so (wall_long - wall_short)/(long - short)
    cancels the per-launch overhead."""

    def make_loop(iters):
        @jax.jit
        def loop(q, kp, vp, bt, sl):
            def body(i, acc):
                # acc-dependent input (not algebraically foldable to q),
                # so the call cannot be hoisted out of the loop
                qq = (q.astype(jnp.float32)
                      * (1 + acc * 1e-10)).astype(q.dtype)
                o = fn(qq, kp, vp, bt, sl)
                return acc + o.astype(jnp.float32).mean()

            return jax.lax.fori_loop(0, iters, body,
                                     jnp.zeros((), jnp.float32))

        return loop

    lo, hi = make_loop(short), make_loop(long)
    # float() forces the device->host readback: over the axon tunnel
    # block_until_ready alone returns before device completion
    float(lo(*args))
    float(hi(*args))
    deltas = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(lo(*args))
        t1 = time.perf_counter()
        float(hi(*args))
        t2 = time.perf_counter()
        deltas.append(((t2 - t1) - (t1 - t0)) / (long - short))
    return float(np.median(deltas))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gqa", action="store_true")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--page", type=int, default=128)
    args = ap.parse_args()

    from paddle_tpu.ops.pallas import decode_attention as da

    B, S, bs = args.batch, args.seq, args.page
    nh, d = 16, 128
    nkv = 4 if args.gqa else nh
    max_blocks = S // bs
    n_pages = B * max_blocks
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, nh, d), jnp.bfloat16)
    k_pages = jnp.asarray(rng.randn(n_pages, nkv, bs, d), jnp.bfloat16)
    v_pages = jnp.asarray(rng.randn(n_pages, nkv, bs, d), jnp.bfloat16)
    kt_pages = jnp.swapaxes(k_pages, 2, 3)
    table = jnp.arange(n_pages, dtype=jnp.int32).reshape(B, max_blocks)
    seq_lens = jnp.full((B,), S, jnp.int32)
    scale = 1.0 / np.sqrt(d)

    kv_bytes = 2 * B * max_blocks * nkv * bs * d * 2   # k+v, bf16

    rows = []
    if nkv == nh and da.paged_decode_supported(k_pages.shape, nh,
                                               max_blocks=max_blocks):
        t = bench(functools.partial(da.paged_decode_attention_kernel,
                                    sm_scale=scale),
                  (q, k_pages, v_pages, table, seq_lens))
        rows.append(("vector(index-map)", t))
    if da.paged_decode_mxu_supported(kt_pages.shape, nh,
                                     max_blocks=max_blocks):
        t = bench(functools.partial(da.paged_decode_attention_mxu,
                                    sm_scale=scale),
                  (q, kt_pages, v_pages, table, seq_lens))
        rows.append(("mxu(blkdiag)", t))

    # XLA gather+dot fallback for context
    def xla_gather(q, kp, vp, bt, sl):
        kg = kp[bt]
        vg = vp[bt]
        kg = jnp.swapaxes(kg, 1, 2).reshape(B, nkv, max_blocks * bs, d)
        vg = jnp.swapaxes(vg, 1, 2).reshape(B, nkv, max_blocks * bs, d)
        if nkv != nh:
            kg = jnp.repeat(kg, nh // nkv, axis=1)
            vg = jnp.repeat(vg, nh // nkv, axis=1)
        s = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32),
                       kg.astype(jnp.float32)) * scale
        pos = jnp.arange(max_blocks * bs)
        s = jnp.where(pos[None, None, :] < sl[:, None, None], s, -1e30)
        p = jax.nn.softmax(s, -1)
        return jnp.einsum("bhk,bhkd->bhd", p.astype(vg.dtype), vg)

    t = bench(xla_gather, (q, k_pages, v_pages, table, seq_lens))
    rows.append(("xla gather+dot", t))

    print(f"B={B} S={S} page={bs} nh={nh} nkv={nkv} d={d} "
          f"kv bytes/call={kv_bytes/2**20:.1f} MiB")
    for name, t in rows:
        print(f"  {name:18s} {t*1e3:7.3f} ms/call  "
              f"{kv_bytes/t/1e9:7.1f} GB/s")


if __name__ == "__main__":
    main()
