"""Ring-attention compiled-program facts (VERDICT r3 weak #2).

AOT-compiles the 1.3B long-context train step on a 4-way virtual mesh
twice — ring attention over the axis vs the Megatron-SP dense path — and
records the collective inventory (the ppermute ring, sizes, replica
groups) and the per-device memory analysis, so the context-parallel
claim rests on compiled-program facts rather than prose.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
       python tools/ring_aot.py [--seq 8192] [--out artifacts/ring_attention_aot.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt3-1.3b")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--out", default="artifacts/ring_attention_aot.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from aot_analyze import analyze

    # ring: sequence sharded over mp, k/v rotating by ppermute
    ring = analyze(args.preset, (1, 1, 4), args.batch, args.seq, 1,
                   ring_axis="mp")
    # baseline: same mesh, Megatron-SP dense/flash attention (the
    # reference's long-context answer — SURVEY §5)
    sp = analyze(args.preset, (1, 1, 4), args.batch, args.seq, 1,
                 ring_axis=None)

    def trim(r):
        return {
            "mesh": r["mesh"], "seq": r["config"]["seq_len"],
            "batch": r["batch_global"], "ring_axis": r["ring_axis"],
            "memory_analysis_per_device": r["memory_analysis_per_device"],
            "collectives_by_kind": r["collectives"]["by_kind"],
            "collective_permutes": [
                c for c in r["collectives"]["instances"]
                if c["kind"] == "collective-permute"],
        }

    out = {
        "purpose": ("ring attention (parallel/ring_attention.py) vs "
                    "Megatron-SP dense attention: compiled 4-way "
                    "long-context train-step programs"),
        "preset": args.preset,
        "ring": trim(ring),
        "sp_dense": trim(sp),
        "delta": {
            "temp_bytes_ring": ring["memory_analysis_per_device"]["temp_bytes"],
            "temp_bytes_sp": sp["memory_analysis_per_device"]["temp_bytes"],
            "temp_ratio_sp_over_ring": round(
                sp["memory_analysis_per_device"]["temp_bytes"]
                / max(1, ring["memory_analysis_per_device"]["temp_bytes"]), 3),
        },
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["delta"]))
    kinds = out["ring"]["collectives_by_kind"]
    print("ring collectives:", json.dumps(kinds))


if __name__ == "__main__":
    main()
