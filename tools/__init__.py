"""Developer tooling for the paddle_tpu tree (lint, bench, profiling)."""
