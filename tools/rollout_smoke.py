"""Zero-downtime rollout smoke gate (ci_check.sh exit 150): a
2-replica FleetRouter mid-decode starts a live weight rollout v1 -> v2
with a chaos ``rollout.swap`` raise armed — the first swap dies
mid-flight. Every accepted request (greedy AND sampled) must still
complete, bit-identical to an uninterrupted solo run on its PINNED
weight version; the fleet must converge to exactly the target version
(the mid-swap corpse is replaced by a fresh engine already on v2); and
every ledger must settle with zero page leak.

Usage:  JAX_PLATFORMS=cpu python -m tools.rollout_smoke
"""

from __future__ import annotations

import sys

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    from paddle_tpu.inference.fleet import FleetRouter
    from paddle_tpu.inference.serving import Request, ServingEngine
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.testing import chaos

    cfg = LlamaConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_hidden=128, max_seq_len=256,
                      dtype=jnp.float32, param_dtype=jnp.float32)
    ekw = dict(max_batch=2, page_size=16, max_seq=128, n_pages=1 + 24,
               prefill_budget=32)
    chaos.arm(chaos.FaultPlan(seed=0)
              .add("rollout.swap", "raise", at=0, engine=0))
    router = FleetRouter(cfg, n_engines=2, seed=0, engine_kwargs=ekw)
    params = router.replicas[0].engine.params

    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, cfg.vocab_size, size=40).astype(np.int32)
               for _ in range(5)]
    reqs = [Request(rid=i, prompt=p, max_new_tokens=12, arrival=0.0)
            for i, p in enumerate(prompts)]
    # one sampled stream: drain/migrate resume bit-identity must hold
    # through the keyed (seed, position) sampling path too, not argmax
    reqs[2].temperature, reqs[2].top_p, reqs[2].seed = 0.8, 0.9, 1234

    for r in reqs:
        router.submit(r, now=1e18)

    # step until some replica holds a mid-decode stream, then deploy —
    # the rollout must drain live streams, not an idle fleet
    mid = False
    for _ in range(200):
        router.step(now=1e18)
        mid = any(r is not None and 0 < len(r.out_tokens)
                  < r.max_new_tokens
                  for rep in router.replicas
                  for r in rep.engine.slots)
        if mid:
            break
    if not mid:
        print("rollout_smoke: FAIL — no mid-decode stream appeared "
              "before the deploy", file=sys.stderr)
        return 1
    v2_params = jax.tree_util.tree_map(
        lambda w: (np.asarray(w) * 1.001).astype(np.asarray(w).dtype),
        params)
    v2 = router.rollout(params=v2_params)

    steps = 0
    while router.step(now=1e18):
        steps += 1
        if steps > 4000:
            print("rollout_smoke: FAIL — fleet did not drain after "
                  "the deploy", file=sys.stderr)
            return 1
    chaos.disarm()

    bad = [r for r in reqs if r.aborted or r.t_done is None
           or len(r.out_tokens) != r.max_new_tokens]
    if bad:
        print(f"rollout_smoke: FAIL — incomplete/aborted requests "
              f"{[r.rid for r in bad]} through the deploy",
              file=sys.stderr)
        return 1
    st = router.fleet_stats()
    if st["n_swap_deaths"] < 1:
        print("rollout_smoke: FAIL — the armed rollout.swap raise "
              "never landed", file=sys.stderr)
        return 1
    if st["fleet_versions"] != [v2]:
        print(f"rollout_smoke: FAIL — fleet did not converge to the "
              f"target version: {st['fleet_versions']} != [{v2}]",
              file=sys.stderr)
        return 1

    # bit-identity: every stream equals an uninterrupted solo run on a
    # fresh engine holding the version the stream was PINNED to
    for r in reqs:
        if r.param_version is None:
            print(f"rollout_smoke: FAIL — rid {r.rid} finished "
                  f"unpinned", file=sys.stderr)
            return 1
        solo_eng = ServingEngine(cfg,
                                 params=router.catalog.get(
                                     r.param_version),
                                 seed=0, **ekw)
        solo = Request(rid=100 + r.rid, prompt=r.prompt.copy(),
                       max_new_tokens=r.max_new_tokens,
                       temperature=r.temperature, top_p=r.top_p,
                       seed=r.seed)
        solo_eng.run([solo])
        if solo.out_tokens != r.out_tokens:
            print(f"rollout_smoke: FAIL — rid {r.rid} stream differs "
                  f"from its uninterrupted run on version "
                  f"{r.param_version}: {r.out_tokens} vs "
                  f"{solo.out_tokens}", file=sys.stderr)
            return 1

    # live ledgers settle to free + cache_idle only; the mid-swap
    # corpse's frozen pool still sums (death loses a replica, not the
    # accounting invariant)
    for rep in router.replicas:
        e = rep.engine
        if rep.alive and (e._deferred_free or e.pool.pending_evict):
            e.pool.release(e._deferred_free)  # tpu-lint: disable=TPL213 -- post-run settlement: run() returned, no program in flight
            e._deferred_free = []
            e.pool.commit_evictable()
        acc = e.page_accounting()
        if acc["total"] != e.n_pages - 1:
            print(f"rollout_smoke: FAIL — engine {e.engine_id} ledger "
                  f"does not sum: {acc}", file=sys.stderr)
            return 1
        if rep.alive and (acc["slot_owned"] or acc["slot_shared"]
                          or acc["deferred_free"] or acc["in_flight"]):
            print(f"rollout_smoke: FAIL — engine {e.engine_id} leaked "
                  f"pages: {acc}", file=sys.stderr)
            return 1

    n_eng = sum(1 for rep in router.replicas if rep.alive)
    print(f"rollout_smoke: OK — deploy v1 -> {v2} survived a mid-swap "
          f"chaos kill ({st['n_swap_deaths']} swap death(s), replaced "
          f"on-target), all 5 streams (incl. sampled) completed "
          f"bit-identically on their pinned versions, {n_eng} live "
          f"engine(s) all on {v2}, ledgers close with no leak")
    return 0


if __name__ == "__main__":
    sys.exit(main())
