"""Loadgen smoke gate (ci_check.sh exit 70): the open-loop traffic
subsystem end to end on CPU — >= 200 seeded Poisson arrivals with a
shared-prefix mix through the unified-step engine under the rush clock,
one mid-run abort. Must complete every non-aborted request, return every
page, and close the occupancy ledger (active + waste buckets == 1).
Catches regressions in arrivals/workload/driver/metrics AND in the
unified scheduler under sustained saturation before a TPU bench round.

Runs TWICE: once on the default fp plane and once with
FLAGS_serving_kv_quant=1 (int8 pages + scale planes), so the quantized
write/rescale/abort paths face the same sustained saturation.

Usage:  JAX_PLATFORMS=cpu python -m tools.loadgen_smoke
"""

from __future__ import annotations

import sys


def _run_pass(label: str) -> int:
    import jax.numpy as jnp

    from paddle_tpu.inference.loadgen import (OpenLoopDriver,
                                              WorkloadSpec, synthesize)
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_hidden=128, max_seq_len=128,
                      dtype=jnp.float32, param_dtype=jnp.float32)
    engine = ServingEngine(cfg, max_batch=3, page_size=16, max_seq=96,
                           n_pages=1 + 16, prefill_budget=32, qb=8)
    if label == "kv_quant" and (not engine._kv_quant
                                or engine.k_pages.dtype != jnp.int8):
        print(f"loadgen_smoke[{label}]: FAIL — serving_kv_quant flag "
              "did not reach the engine", file=sys.stderr)
        return 1
    spec = WorkloadSpec(n_requests=200, seed=0, vocab_size=256,
                        process="poisson", rate=100.0,
                        prefix_len=16, n_prefixes=2, shared_frac=0.6,
                        tail_log_mean=2.6, tail_log_sigma=0.7,
                        tail_min=2, tail_max=48, new_min=2, new_max=6,
                        sampled_frac=0.25, max_seq=96)
    reqs = synthesize(spec)
    driver = OpenLoopDriver(engine, clock="rush")
    try:
        m = driver.run(reqs, aborts={5: 17})
    except RuntimeError as e:
        print(f"loadgen_smoke[{label}]: FAIL — {e}", file=sys.stderr)
        return 1
    if m["n_aborted"] != 1 or not reqs[17].aborted:
        print(f"loadgen_smoke[{label}]: FAIL — mid-run abort did not "
              "fire", file=sys.stderr)
        return 1
    incomplete = [r.rid for r in reqs if not r.aborted
                  and (len(r.out_tokens) != r.max_new_tokens
                       or r.t_done is None)]
    if incomplete:
        print(f"loadgen_smoke[{label}]: FAIL — incomplete requests "
              f"{incomplete}", file=sys.stderr)
        return 1
    acc = engine.page_accounting()
    if (acc["total"] != engine.n_pages - 1 or acc["slot_owned"]
            or acc["deferred_free"]):
        print(f"loadgen_smoke[{label}]: FAIL — page leak: {acc}",
              file=sys.stderr)
        return 1
    occ = (m["slot_occupancy"] + m["occ_waste_queue_empty"]
           + m["occ_waste_admission_blocked"] + m["occ_waste_prefill"]
           + m["occ_waste_overrun"] + m["occ_waste_spec_rejected"])
    if abs(occ - 1.0) > 0.01:
        print(f"loadgen_smoke[{label}]: FAIL — occupancy ledger does "
              f"not close: {occ} != 1 ({m})", file=sys.stderr)
        return 1
    print(f"loadgen_smoke[{label}]: OK — {m['n_completed']}/"
          f"{m['n_requests']} requests (+1 abort) in {m['steps']} steps, "
          f"occupancy {m['slot_occupancy']}, goodput "
          f"{m['goodput_tok_s']} tok/s, "
          f"{engine.kv_bytes_per_token():.0f} KV B/tok, no leak")
    return 0


def main() -> int:
    from paddle_tpu.core.flags import GLOBAL_FLAGS

    rc = _run_pass("fp")
    if rc:
        return rc
    prev = GLOBAL_FLAGS.get("serving_kv_quant")
    GLOBAL_FLAGS.set("serving_kv_quant", True)
    try:
        return _run_pass("kv_quant")
    finally:
        GLOBAL_FLAGS.set("serving_kv_quant", prev)


if __name__ == "__main__":
    sys.exit(main())
