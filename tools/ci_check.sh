#!/usr/bin/env bash
# One-command CI gate: static analysis -> op-contract baseline -> chaos
# suite -> serving smoke -> kernel parity -> loadgen smoke -> multichip
# smoke -> multitenant smoke -> fleet smoke -> disagg smoke -> fusion
# smoke -> shardcheck smoke -> quantcheck smoke -> rollout smoke ->
# obs smoke -> tier-1.
#
#   bash tools/ci_check.sh
#
# Distinct exit codes per failing stage (stable; see
# tools/lint/ARCHITECTURE.md):
#   10  tpu-lint findings (or lint driver error)
#   20  op-contract violations / baseline drift / missing baseline
#   40  chaos suite failed (fault injection / self-healing regressions)
#   50  serving smoke failed (scheduler completion / page-leak check)
#   60  kernel parity failed (fused kernel != unfused composition)
#   70  loadgen smoke failed (open-loop saturation / occupancy ledger)
#   80  multichip smoke failed (remat regression / serial-parity drift /
#       quantized all-reduce divergence on the 8-device virtual mesh)
#   90  multitenant smoke failed (adapter isolation / preemption /
#       constrained-stream legality / 7-class page-ledger leak)
#  100  fleet smoke failed (engine-loss recovery: a victim stream was
#       dropped or diverged, no pages migrated, or the survivor leaked)
#  110  disagg smoke failed (prefill-pool loss: no pages adopted over
#       the prefill->decode wire, degraded-mode completion dropped or
#       diverged a stream, or a surviving ledger leaked)
#  120  fusion smoke failed (the jaxpr pass found <3 sites on the seeded
#       config, eager fused loss drifted from the unfused composition,
#       or the per-program autotune cache failed to replay on restart)
#  130  shardcheck smoke failed (unexplained static sharding/collective
#       finding on a registered entry program, stale explanation, or
#       drift against artifacts/shardcheck.json)
#  140  quantcheck smoke failed (unexplained precision/scale-provenance
#       finding on a registered entry program, format-environment drift
#       against artifacts/quantcheck.json, or the TPL303 scale-leak
#       regression harness no longer fires exactly once on the pre-fix
#       admission program while staying silent on the shipped one)
#  150  rollout smoke failed (live weight rollout under a mid-swap chaos
#       kill: a stream was dropped, diverged from its pinned version,
#       the fleet did not converge to the target version, or a ledger
#       leaked)
#  160  obs smoke failed (armed tracing through an engine kill produced
#       an invalid Chrome trace, lost the migration span or chaos
#       annotation, failed to flight-dump on the death path, leaked
#       pages, or perturbed a token stream vs the disarmed control run)
#   30  tier-1 tests failed (ROADMAP.md command)
#    0  all gates green
set -u
cd "$(dirname "$0")/.."

echo "== gate 1/16: tpu-lint (per-file + interprocedural + typestate rules) =="
python -m tools.lint paddle_tpu tests tools --format=json > /tmp/tpu_lint.json
rc=$?
if [ "$rc" -ne 0 ]; then
    cat /tmp/tpu_lint.json
    echo "ci_check: tpu-lint gate failed (lint rc=$rc)" >&2
    exit 10
fi
echo "tpu-lint: clean"

echo "== gate 2/16: tpu-verify (abstract op-contract baseline) =="
JAX_PLATFORMS=cpu python -m tools.lint --contracts \
    --baseline artifacts/op_contracts.json
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: contract gate failed (verify rc=$rc; regenerate" \
         "deliberately with --write-baseline)" >&2
    exit 20
fi

echo "== gate 3/16: chaos suite (fault injection -> self-healing) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: chaos gate failed (pytest rc=$rc) — a fault class" \
         "is no longer detected/recovered" >&2
    exit 40
fi

echo "== gate 4/16: serving smoke (scheduler completion + zero page leak) =="
JAX_PLATFORMS=cpu python -m tools.serving_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: serving smoke gate failed (rc=$rc) — the scheduler" \
         "dropped a request or leaked pages" >&2
    exit 50
fi

echo "== gate 5/16: kernel parity (fused megakernels, CPU fallback arms) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_fused_norm_epilogue.py \
    tests/test_fused_rope_attention.py tests/test_autotune.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: kernel parity gate failed (pytest rc=$rc) — a fused" \
         "kernel no longer matches its unfused composition bit-for-bit" >&2
    exit 60
fi

echo "== gate 6/16: loadgen smoke (open-loop saturation, >=200 arrivals) =="
JAX_PLATFORMS=cpu python -m tools.loadgen_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: loadgen smoke gate failed (rc=$rc) — the open-loop" \
         "driver dropped work, leaked pages, or the occupancy ledger" \
         "no longer closes" >&2
    exit 70
fi

echo "== gate 7/16: multichip smoke (dp x mp mesh: remat-free compile," \
     "serial parity, quantized all-reduce) =="
python tools/multichip_smoke.py
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: multichip smoke gate failed (rc=$rc) — the sharded" \
         "train step rematerializes, drifted from the serial step, or the" \
         "quantized all-reduce diverged" >&2
    exit 80
fi

echo "== gate 8/16: multitenant smoke (LoRA isolation, preemption," \
     "constrained legality, 7-class ledger) =="
JAX_PLATFORMS=cpu python -m tools.multitenant_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: multitenant smoke gate failed (rc=$rc) — an adapter" \
         "stream leaked across tenants, preemption broke a stream, a" \
         "constrained request emitted an illegal token, or the 7-class" \
         "page ledger no longer closes" >&2
    exit 90
fi

echo "== gate 9/16: fleet smoke (engine loss -> bit-identical resume," \
     "page migration, survivor ledger) =="
JAX_PLATFORMS=cpu python -m tools.fleet_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: fleet smoke gate failed (rc=$rc) — killing a replica" \
         "mid-decode dropped or diverged a stream, migrated no pages, or" \
         "left the survivor's page ledger open" >&2
    exit 100
fi

echo "== gate 10/16: disagg smoke (prefill-pool loss -> degraded" \
     "colocated completion, shipped pages, surviving ledgers) =="
JAX_PLATFORMS=cpu python -m tools.disagg_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: disagg smoke gate failed (rc=$rc) — killing the" \
         "prefill pool mid-shipment dropped or diverged a stream, no" \
         "pages were adopted pre-kill, or a surviving engine leaked" >&2
    exit 110
fi

echo "== gate 11/16: fusion smoke (jaxpr fusion discovery, eager" \
     "parity, per-program autotune replay) =="
JAX_PLATFORMS=cpu python -m tools.fusion_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: fusion smoke gate failed (rc=$rc) — the fusion pass" \
         "lost a discovered site, broke eager bit-parity against the" \
         "unfused composition, or the v2 program cache no longer" \
         "replays without sweeping" >&2
    exit 120
fi

echo "== gate 12/16: shardcheck smoke (static sharding/collective" \
     "verification over the registered entry programs) =="
JAX_PLATFORMS=cpu python -m tools.lint --shardcheck \
    --baseline artifacts/shardcheck.json
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: shardcheck gate failed (rc=$rc) — an entry program" \
         "has an unexplained involuntary-reshard/collective finding, an" \
         "explanation went stale, or the spec environment drifted from" \
         "artifacts/shardcheck.json (regenerate deliberately with" \
         "--write-baseline)" >&2
    exit 130
fi

echo "== gate 13/16: quantcheck smoke (static precision & scale-provenance" \
     "verification + TPL303 scale-leak regression harness) =="
JAX_PLATFORMS=cpu python -m tools.lint --quantcheck \
    --baseline artifacts/quantcheck.json
rc=$?
if [ "$rc" -eq 0 ]; then
    JAX_PLATFORMS=cpu python -m tools.lint --quantcheck-regression
    rc=$?
fi
if [ "$rc" -ne 0 ]; then
    echo "ci_check: quantcheck gate failed (rc=$rc) — an entry program" \
         "has an unexplained precision/scale-provenance finding" \
         "(TPL300-TPL305), an explanation went stale, the format" \
         "environment drifted from artifacts/quantcheck.json (regenerate" \
         "deliberately with --write-baseline), or the scale-leak" \
         "regression harness lost its exactly-once TPL303 signal" >&2
    exit 140
fi

echo "== gate 14/16: rollout smoke (live weight deploy under a mid-swap" \
     "chaos kill -> pinned-version bit-identity, single-version" \
     "convergence, zero leak) =="
JAX_PLATFORMS=cpu python -m tools.rollout_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: rollout smoke gate failed (rc=$rc) — a mid-swap" \
         "death dropped or diverged a stream, the fleet ended on a" \
         "mixed/wrong weight version, or a page ledger leaked" >&2
    exit 150
fi

echo "== gate 15/16: obs smoke (armed tracing through an engine kill ->" \
     "valid trace + migration span + fault annotation + flight dump," \
     "disarmed control bit-identical) =="
JAX_PLATFORMS=cpu python -m tools.obs_smoke
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "ci_check: obs smoke gate failed (rc=$rc) — the armed trace" \
         "went structurally invalid, lost the migration/chaos evidence," \
         "the death path stopped flight-dumping, a ledger leaked, or" \
         "tracing perturbed a token stream" >&2
    exit 160
fi

echo "== gate 16/16: tier-1 tests (ROADMAP.md) =="

set -o pipefail
rm -f /tmp/_t1.log
# budget raised 870 -> 1200 -> 1800: the suite is ~1300s single-process
# as of PR 19 (888 tests; growth is spread across rounds, top offenders
# are the lint/contract sweeps) — keep headroom so a green suite can't
# time out
timeout -k 10 1800 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "ci_check: tier-1 gate failed (pytest rc=$rc)" >&2
    exit 30
fi

echo "ci_check: all gates green"
