"""Observability smoke gate (ci_check.sh exit 160): a 2-replica fleet
takes a chaos engine kill mid-decode with the obs plane ARMED — the
exported Chrome trace must be structurally valid (B/E balanced, async
request flows closed), contain at least one ``fleet.migrate`` span and
the ``chaos.engine.step`` fault annotation, a flight record must have
auto-dumped on the death path naming the injected fault, and every
surviving page ledger must close with zero leak. A second DISARMED pass
under the identical chaos plan must then produce bit-identical token
streams: tracing observes the fleet, it never steers it.

Usage:  JAX_PLATFORMS=cpu python -m tools.obs_smoke
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np


def _mk_reqs(cfg):
    from paddle_tpu.inference.serving import Request

    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, cfg.vocab_size, size=40).astype(np.int32)
               for _ in range(5)]
    reqs = [Request(rid=i, prompt=p.copy(), max_new_tokens=12,
                    arrival=0.0) for i, p in enumerate(prompts)]
    # one keyed-sampling stream: non-perturbation must hold through the
    # (seed, position) sampling path too, not just argmax
    reqs[2].temperature, reqs[2].top_p, reqs[2].seed = 0.8, 0.9, 1234
    return reqs


def _run_fleet(cfg, ekw, kill: bool) -> list:
    """One fleet pass under the standard chaos kill; returns requests."""
    from paddle_tpu.inference.fleet import FleetRouter
    from paddle_tpu.testing import chaos

    if kill:
        chaos.arm(chaos.FaultPlan(seed=0)
                  .add("engine.step", "raise", at=6, engine=0))
    router = FleetRouter(cfg, n_engines=2, seed=0, engine_kwargs=ekw)
    reqs = _mk_reqs(cfg)
    for r in reqs:
        router.submit(r, now=1e18)
    steps = 0
    while router.step(now=1e18):
        steps += 1
        if steps > 4000:
            raise RuntimeError("fleet did not drain")
    chaos.disarm()
    return reqs, router


def _check_ledgers(router) -> str:
    for rep in router.replicas:
        e = rep.engine
        if rep.alive and (e._deferred_free or e.pool.pending_evict):
            e.pool.release(e._deferred_free)  # tpu-lint: disable=TPL213 -- post-run settlement: drained, no program in flight
            e._deferred_free = []
            e.pool.commit_evictable()
        acc = e.page_accounting()
        if acc["total"] != e.n_pages - 1:
            return f"engine {e.engine_id} ledger does not sum: {acc}"
        if rep.alive and (acc["slot_owned"] or acc["slot_shared"]
                          or acc["deferred_free"] or acc["in_flight"]):
            return f"engine {e.engine_id} leaked pages: {acc}"
    return ""


def _check_trace(doc) -> str:
    """Perfetto's structural contract: balanced B/E per track, every
    async end opened by a begin."""
    json.loads(json.dumps(doc))
    stacks: dict = {}
    opened: dict = {}
    for ev in doc["traceEvents"]:
        ph = ev["ph"]
        if ph == "B":
            stacks.setdefault(ev["tid"], []).append(ev["name"])
        elif ph == "E":
            if not stacks.get(ev["tid"]):
                return f"orphan E event {ev}"
            stacks[ev["tid"]].pop()
        elif ph == "b":
            k = (ev["name"], ev["id"])
            opened[k] = opened.get(k, 0) + 1
        elif ph == "e":
            k = (ev["name"], ev["id"])
            if not opened.get(k):
                return f"orphan async end {ev}"
            opened[k] -= 1
    if any(s for s in stacks.values()):
        return f"unbalanced B/E stacks: {stacks}"
    if any(n for n in opened.values()):
        return f"unclosed async flows: {opened}"
    return ""


def main() -> int:
    import jax.numpy as jnp

    from paddle_tpu import obs
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_hidden=128, max_seq_len=256,
                      dtype=jnp.float32, param_dtype=jnp.float32)
    ekw = dict(max_batch=2, page_size=16, max_seq=128, n_pages=1 + 24,
               prefill_budget=32)

    # -- pass 1: ARMED, chaos kill ------------------------------------------
    st = obs.arm(capacity=16384, dump_dir="artifacts")
    armed_reqs, router = _run_fleet(cfg, ekw, kill=True)
    bad = [r.rid for r in armed_reqs if r.aborted or r.t_done is None
           or len(r.out_tokens) != r.max_new_tokens]
    if bad:
        print(f"obs_smoke: FAIL — requests {bad} dropped through the "
              f"kill", file=sys.stderr)
        return 1
    if router.stats["n_killed"] != 1:
        print("obs_smoke: FAIL — the armed engine.step raise never "
              "landed", file=sys.stderr)
        return 1

    doc = obs.export()
    err = _check_trace(doc)
    if err:
        print(f"obs_smoke: FAIL — invalid Chrome trace: {err}",
              file=sys.stderr)
        return 1
    names = {e["name"] for e in doc["traceEvents"]}
    migrates = [e for e in doc["traceEvents"]
                if e["ph"] == "B" and e["name"] == "fleet.migrate"]
    if not migrates:
        print("obs_smoke: FAIL — no fleet.migrate span in the trace of "
              "a run that migrated pages", file=sys.stderr)
        return 1
    if "chaos.engine.step" not in names:
        print("obs_smoke: FAIL — the fired chaos fault was not "
              "annotated into the trace", file=sys.stderr)
        return 1
    if len(st.dumps) != 1:
        print(f"obs_smoke: FAIL — expected exactly one flight dump on "
              f"the death path, got {st.dumps}", file=sys.stderr)
        return 1
    rec = json.load(open(st.dumps[0]))
    if rec["schema"] != "paddle_tpu.flightrec.v1" \
            or rec["reason"] != "engine-death" \
            or [f["point"] for f in rec["faults"]] != ["engine.step"]:
        print(f"obs_smoke: FAIL — flight record does not name its "
              f"killer: {rec['reason']}, {rec['faults']}",
              file=sys.stderr)
        return 1
    err = _check_ledgers(router)
    if err:
        print(f"obs_smoke: FAIL — {err}", file=sys.stderr)
        return 1
    obs.disarm()

    # -- pass 2: DISARMED, identical chaos plan -> identical streams --------
    plain_reqs, router2 = _run_fleet(cfg, ekw, kill=True)
    if obs.active():
        print("obs_smoke: FAIL — obs still armed in the control pass",
              file=sys.stderr)
        return 1
    for a, b in zip(armed_reqs, plain_reqs):
        if a.out_tokens != b.out_tokens:
            print(f"obs_smoke: FAIL — rid {a.rid} stream differs with "
                  f"tracing armed vs disarmed: {a.out_tokens} vs "
                  f"{b.out_tokens}", file=sys.stderr)
            return 1
    err = _check_ledgers(router2)
    if err:
        print(f"obs_smoke: FAIL — control pass: {err}", file=sys.stderr)
        return 1

    print(f"obs_smoke: OK — {len(armed_reqs)} streams bit-identical "
          f"armed vs disarmed through an engine kill; "
          f"{len(migrates)} migration span(s), fault annotated, "
          f"flight record {os.path.basename(st.dumps[0])}, "
          f"ledgers closed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
