"""tpu-lint driver: file discovery, checker orchestration, CLI.

    python -m tools.lint paddle_tpu tests [--format=json] [--select=TPL001]

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import os
import sys

from .checkers import ALL_CHECKERS
from .core import Finding, parse_file
from .reporters import render_json, render_text

__all__ = ["run_lint", "main", "iter_python_files"]

# Fixture files contain *seeded* violations for the checker unit tests —
# never part of a clean-tree run.
DEFAULT_EXCLUDES = ("data/lint_fixtures",)


def iter_python_files(paths: list[str],
                      excludes: tuple = DEFAULT_EXCLUDES) -> list[str]:
    out = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for fn in sorted(files):
                if fn.endswith(".py"):
                    out.append(os.path.join(root, fn))
    norm = [p.replace(os.sep, "/") for p in out]
    return [p for p, n in zip(out, norm)
            if not any(ex in n for ex in excludes)]


def run_lint(paths: list[str], select: set[str] | None = None,
             excludes: tuple = DEFAULT_EXCLUDES,
             keep_suppressed: bool = False) -> list[Finding]:
    """Run every (selected) checker over the python files under ``paths``
    and return unsuppressed findings, sorted by location."""
    checkers = [cls() for cls in ALL_CHECKERS
                if select is None
                or cls.rule in select or cls.name in select]
    findings: list[Finding] = []
    contexts = {}
    for path in iter_python_files(paths, excludes):
        display = path.replace(os.sep, "/")
        ctx, err = parse_file(path, display)
        if err is not None:
            findings.append(err)
            continue
        contexts[display] = ctx
        for checker in checkers:
            checker.check(ctx)
    for checker in checkers:
        checker.finalize()
        findings.extend(checker.findings)
    if not keep_suppressed:
        findings = [
            f for f in findings
            if f.path not in contexts
            or not contexts[f.path].suppressions.matches(f)
        ]
    return sorted(findings, key=Finding.sort_key)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.lint",
        description="tpu-lint: static trace-safety/aliasing/registry "
                    "checks for the paddle_tpu tree.",
    )
    parser.add_argument("paths", nargs="*", default=["paddle_tpu", "tests"],
                        help="files or directories to lint "
                             "(default: paddle_tpu tests)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", help="output format")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids/names to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--no-default-excludes", action="store_true",
                        help="also lint the seeded-violation fixtures")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_CHECKERS:
            print(f"{cls.rule}  {cls.name:<20} {cls.severity:<8} "
                  f"{cls.description}")
        return 0

    paths = args.paths or ["paddle_tpu", "tests"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpu-lint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    select = ({s.strip() for s in args.select.split(",") if s.strip()}
              if args.select else None)
    excludes = () if args.no_default_excludes else DEFAULT_EXCLUDES
    findings = run_lint(paths, select=select, excludes=excludes)
    render = render_json if args.format == "json" else render_text
    print(render(findings))
    return 1 if findings else 0
