"""tpu-lint driver: file discovery, checker orchestration, CLI.

    python -m tools.lint paddle_tpu tests [--format=json] [--select=TPL001]
    python -m tools.lint --contracts --baseline artifacts/op_contracts.json
    python -m tools.lint --contracts --baseline ... --write-baseline

Exit codes (stable; tools/ci_check.sh relies on them):
  0  clean / baseline matches
  1  lint findings, unexplained contract violations, or baseline drift
  2  usage/internal error
  3  --baseline file missing (run with --write-baseline first)
"""

from __future__ import annotations

import argparse
import os
import sys

from .checkers import ALL_CHECKERS as FILE_CHECKERS
from .core import Finding, parse_file
from .interproc import INTERPROC_CHECKERS, ProjectIndex
from .reporters import render_json, render_text

__all__ = ["ALL_CHECKERS", "run_lint", "main", "iter_python_files"]

ALL_CHECKERS = list(FILE_CHECKERS) + list(INTERPROC_CHECKERS)

# Fixture files contain *seeded* violations for the checker unit tests —
# never part of a clean-tree run.
DEFAULT_EXCLUDES = ("data/lint_fixtures",)


def _is_excluded(norm_path: str, excludes: tuple) -> bool:
    """Anchored on path components: ``data/lint_fixtures`` matches that
    exact directory sequence anywhere in the path — but not substrings
    of component names (``mydata/lint_fixtures_old`` stays included)."""
    parts = norm_path.split("/")
    for ex in excludes:
        ex_parts = [p for p in ex.replace(os.sep, "/").split("/") if p]
        n = len(ex_parts)
        if n and any(parts[i:i + n] == ex_parts
                     for i in range(len(parts) - n + 1)):
            return True
    return False


def iter_python_files(paths: list[str],
                      excludes: tuple = DEFAULT_EXCLUDES) -> list[str]:
    out = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for fn in sorted(files):
                if fn.endswith(".py"):
                    out.append(os.path.join(root, fn))
    return [p for p in out
            if not _is_excluded(p.replace(os.sep, "/"), excludes)]


def run_lint(paths: list[str], select: set[str] | None = None,
             excludes: tuple = DEFAULT_EXCLUDES,
             keep_suppressed: bool = False) -> list[Finding]:
    """Run every (selected) checker over the python files under ``paths``
    and return unsuppressed findings, sorted by location.

    Checkers with ``needs_project = True`` (tools/lint/interproc.py) get
    a shared :class:`ProjectIndex` bound as ``checker.project``, fed one
    summary per parsed file; they report whole-program findings from
    ``finalize()``."""
    checkers = [cls() for cls in ALL_CHECKERS
                if select is None
                or cls.rule in select or cls.name in select]
    project = ProjectIndex()
    bound = [c for c in checkers if getattr(c, "needs_project", False)]
    for checker in bound:
        checker.project = project
    findings: list[Finding] = []
    contexts = {}
    for path in iter_python_files(paths, excludes):
        display = path.replace(os.sep, "/")
        ctx, err = parse_file(path, display)
        if err is not None:
            findings.append(err)
            continue
        contexts[display] = ctx
        if bound:
            project.add_file(ctx)
        for checker in checkers:
            checker.check(ctx)
    for checker in checkers:
        checker.finalize()
        findings.extend(checker.findings)
    if not keep_suppressed:
        findings = [
            f for f in findings
            if f.path not in contexts
            or not contexts[f.path].suppressions.matches(f)
        ]
    return sorted(findings, key=Finding.sort_key)


def run_contracts(baseline: str | None, write: bool,
                  fmt: str = "text") -> int:
    """Abstract op-contract verification (tools/lint/contracts.py)."""
    from . import contracts as C

    if baseline and not write and not os.path.exists(baseline):
        print(f"tpu-verify: baseline {baseline} missing "
              "(run with --write-baseline)", file=sys.stderr)
        return 3
    current = C.build_contracts()
    bad = C.unexplained_violations(current)
    drift: list[str] = []
    if baseline:
        if write:
            C.write_baseline(current, baseline)
        else:
            drift = C.diff_baselines(current, C.load_baseline(baseline))
    if fmt == "json":
        import json

        print(json.dumps({"summary": current["summary"],
                          "unexplained": [list(v) for v in bad],
                          "drift": drift}, indent=2))
    else:
        for name, kind, detail in bad:
            print(f"op '{name}': {kind}: {detail}")
        for line in drift:
            print(line)
        s = current["summary"]
        print(f"tpu-verify: {current['op_count']} ops, {s['ok']} "
              f"abstractly evaluated, {s['opaque']} opaque, "
              f"{len(bad)} unexplained violation(s), "
              f"{len(drift)} baseline drift line(s)"
              + (f" -> wrote {baseline}" if write and baseline else ""))
    return 1 if bad or drift else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.lint",
        description="tpu-lint: static trace-safety/aliasing/registry "
                    "checks plus abstract op-contract verification "
                    "for the paddle_tpu tree.",
    )
    parser.add_argument("paths", nargs="*", default=["paddle_tpu", "tests"],
                        help="files or directories to lint "
                             "(default: paddle_tpu tests)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", help="output format")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids/names to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--no-default-excludes", action="store_true",
                        help="also lint the seeded-violation fixtures")
    parser.add_argument("--contracts", action="store_true",
                        help="run abstract op-contract verification over "
                             "the dispatch registry instead of lint")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="with --contracts: compare against (or, with "
                             "--write-baseline, regenerate) this JSON "
                             "baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="with --contracts --baseline: write the "
                             "baseline instead of diffing")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_CHECKERS:
            print(f"{cls.rule}  {cls.name:<20} {cls.severity:<8} "
                  f"{cls.description}")
        return 0

    if args.write_baseline and not (args.contracts and args.baseline):
        print("tpu-lint: --write-baseline requires --contracts and "
              "--baseline PATH", file=sys.stderr)
        return 2
    if args.contracts:
        try:
            return run_contracts(args.baseline, args.write_baseline,
                                 args.format)
        except ImportError as e:
            print(f"tpu-verify: registry import failed: {e}",
                  file=sys.stderr)
            return 2

    paths = args.paths or ["paddle_tpu", "tests"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpu-lint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    select = ({s.strip() for s in args.select.split(",") if s.strip()}
              if args.select else None)
    excludes = () if args.no_default_excludes else DEFAULT_EXCLUDES
    findings = run_lint(paths, select=select, excludes=excludes)
    render = render_json if args.format == "json" else render_text
    print(render(findings))
    return 1 if findings else 0
