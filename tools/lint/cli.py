"""tpu-lint driver: file discovery, checker orchestration, CLI.

    python -m tools.lint paddle_tpu tests [--format=json] [--select=TPL001]
    python -m tools.lint --contracts --baseline artifacts/op_contracts.json
    python -m tools.lint --contracts --baseline ... --write-baseline
    python -m tools.lint --shardcheck --baseline artifacts/shardcheck.json
    python -m tools.lint --quantcheck --baseline artifacts/quantcheck.json
    python -m tools.lint --quantcheck-regression

Exit codes (stable; tools/ci_check.sh relies on them):
  0  clean / baseline matches
  1  lint findings, unexplained contract violations, or baseline drift
  2  usage/internal error
  3  --baseline file missing (run with --write-baseline first)
"""

from __future__ import annotations

import argparse
import os
import sys

from .checkers import ALL_CHECKERS as FILE_CHECKERS
from .core import Finding, parse_file
from .interproc import INTERPROC_CHECKERS, ProjectIndex
from .reporters import render_json, render_sarif, render_text
from .typestate import TYPESTATE_CHECKERS

__all__ = ["ALL_CHECKERS", "run_lint", "main", "iter_python_files"]

ALL_CHECKERS = (list(FILE_CHECKERS) + list(INTERPROC_CHECKERS)
                + list(TYPESTATE_CHECKERS))

# Fixture files contain *seeded* violations for the checker unit tests —
# never part of a clean-tree run.
DEFAULT_EXCLUDES = ("data/lint_fixtures",)


def _is_excluded(norm_path: str, excludes: tuple) -> bool:
    """Anchored on path components: ``data/lint_fixtures`` matches that
    exact directory sequence anywhere in the path — but not substrings
    of component names (``mydata/lint_fixtures_old`` stays included)."""
    parts = norm_path.split("/")
    for ex in excludes:
        ex_parts = [p for p in ex.replace(os.sep, "/").split("/") if p]
        n = len(ex_parts)
        if n and any(parts[i:i + n] == ex_parts
                     for i in range(len(parts) - n + 1)):
            return True
    return False


def iter_python_files(paths: list[str],
                      excludes: tuple = DEFAULT_EXCLUDES) -> list[str]:
    out = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for fn in sorted(files):
                if fn.endswith(".py"):
                    out.append(os.path.join(root, fn))
    return [p for p in out
            if not _is_excluded(p.replace(os.sep, "/"), excludes)]


def run_lint(paths: list[str], select: set[str] | None = None,
             excludes: tuple = DEFAULT_EXCLUDES,
             keep_suppressed: bool = False,
             ignore: set[str] | None = None) -> list[Finding]:
    """Run every (selected) checker over the python files under ``paths``
    and return unsuppressed findings, sorted by location.

    ``select`` keeps only the named rules; ``ignore`` then drops rules
    from that set (ids or slugs, like suppressions).

    Checkers with ``needs_project = True`` (tools/lint/interproc.py) get
    a shared :class:`ProjectIndex` bound as ``checker.project``, fed one
    summary per parsed file; they report whole-program findings from
    ``finalize()``."""
    checkers = [cls() for cls in ALL_CHECKERS
                if select is None
                or cls.rule in select or cls.name in select]
    if ignore:
        checkers = [c for c in checkers
                    if c.rule not in ignore and c.name not in ignore]
    project = ProjectIndex()
    bound = [c for c in checkers if getattr(c, "needs_project", False)]
    for checker in bound:
        checker.project = project
    findings: list[Finding] = []
    contexts = {}
    for path in iter_python_files(paths, excludes):
        display = path.replace(os.sep, "/")
        ctx, err = parse_file(path, display)
        if err is not None:
            findings.append(err)
            continue
        contexts[display] = ctx
        if bound:
            project.add_file(ctx)
        for checker in checkers:
            checker.check(ctx)
    for checker in checkers:
        checker.finalize()
        findings.extend(checker.findings)
    if not keep_suppressed:
        findings = [
            f for f in findings
            if f.path not in contexts
            or not contexts[f.path].suppressions.matches(f)
        ]
    return sorted(findings, key=Finding.sort_key)


def run_contracts(baseline: str | None, write: bool,
                  fmt: str = "text") -> int:
    """Abstract op-contract verification (tools/lint/contracts.py)."""
    from . import contracts as C

    if baseline and not write and not os.path.exists(baseline):
        print(f"tpu-verify: baseline {baseline} missing "
              "(run with --write-baseline)", file=sys.stderr)
        return 3
    current = C.build_contracts()
    bad = C.unexplained_violations(current)
    drift: list[str] = []
    if baseline:
        if write:
            C.write_baseline(current, baseline)
        else:
            drift = C.diff_baselines(current, C.load_baseline(baseline))
    if fmt == "json":
        import json

        print(json.dumps({"summary": current["summary"],
                          "unexplained": [list(v) for v in bad],
                          "drift": drift}, indent=2))
    else:
        for name, kind, detail in bad:
            print(f"op '{name}': {kind}: {detail}")
        for line in drift:
            print(line)
        s = current["summary"]
        print(f"tpu-verify: {current['op_count']} ops, {s['ok']} "
              f"abstractly evaluated, {s['opaque']} opaque, "
              f"{len(bad)} unexplained violation(s), "
              f"{len(drift)} baseline drift line(s)"
              + (f" -> wrote {baseline}" if write and baseline else ""))
    return 1 if bad or drift else 0


def run_shardcheck(baseline: str | None, write: bool,
                   fmt: str = "text") -> int:
    """Static sharding & collective verification over the registered
    entry programs (tools/lint/shardcheck.py). Same exit-code contract
    as run_contracts: 0 clean/matching, 1 unexplained findings or
    drift, 3 missing baseline."""
    from . import shardcheck as S

    if baseline and not write and not os.path.exists(baseline):
        print(f"shardcheck: baseline {baseline} missing "
              "(run with --write-baseline)", file=sys.stderr)
        return 3
    report = S.build_report()
    findings = report["findings"]
    bad = S.unexplained_findings(findings)
    stale = S.stale_explanations(findings)
    drift: list[str] = []
    if baseline:
        if write:
            S.write_baseline(report["baseline"], baseline)
        else:
            drift = S.diff_baselines(report["baseline"],
                                     S.load_baseline(baseline))
    entries = report["baseline"]["entries"]
    if fmt == "json":
        import json

        print(json.dumps({
            "entries": entries,
            "findings": [f.as_dict() for f in findings],
            "unexplained": [f.as_dict() for f in bad],
            "stale_explanations": stale,
            "drift": drift,
        }, indent=2))
    elif fmt == "sarif":
        print(render_sarif(bad, tool_name="tpu-shardcheck"))
    else:
        if bad:
            print(render_text(bad))
        for line in stale:
            print(line)
        for line in drift:
            print(line)
        n_explained = len(findings) - len(bad)
        print(f"shardcheck: {len(entries)} entry program(s), "
              f"{len(bad)} unexplained finding(s), {n_explained} "
              f"explained, {len(stale)} stale explanation(s), "
              f"{len(drift)} baseline drift line(s)"
              + (f" -> wrote {baseline}" if write and baseline else ""))
    return 1 if bad or drift or stale else 0


def run_quantcheck(baseline: str | None, write: bool, fmt: str = "text",
                   select: set[str] | None = None,
                   ignore: set[str] | None = None) -> int:
    """Static precision & scale-provenance verification over the
    registered entry programs (tools/lint/quantcheck.py).  Same exit-
    code contract as run_shardcheck; ``select``/``ignore`` filter the
    *reported* unexplained findings by rule id or slug (the baseline
    payload always covers every rule, so a filtered run cannot write a
    narrowed baseline)."""
    from . import quantcheck as Q

    if baseline and not write and not os.path.exists(baseline):
        print(f"quantcheck: baseline {baseline} missing "
              "(run with --write-baseline)", file=sys.stderr)
        return 3
    report = Q.build_report()
    findings = report["findings"]
    bad = Q.unexplained_findings(findings)
    if select:
        bad = [f for f in bad if f.rule in select or f.name in select]
    if ignore:
        bad = [f for f in bad
               if f.rule not in ignore and f.name not in ignore]
    stale = Q.stale_explanations(findings)
    drift: list[str] = []
    if baseline:
        if write:
            Q.write_baseline(report["baseline"], baseline)
        else:
            drift = Q.diff_baselines(report["baseline"],
                                     Q.load_baseline(baseline))
    entries = report["baseline"]["entries"]
    if fmt == "json":
        import json

        print(json.dumps({
            "entries": entries,
            "kernel_accum": report["baseline"]["kernel_accum"],
            "findings": [f.as_dict() for f in findings],
            "unexplained": [f.as_dict() for f in bad],
            "stale_explanations": stale,
            "drift": drift,
        }, indent=2))
    elif fmt == "sarif":
        print(render_sarif(bad, tool_name="tpu-quantcheck"))
    else:
        if bad:
            print(render_text(bad))
        for line in stale:
            print(line)
        for line in drift:
            print(line)
        n_explained = len(findings) - len(Q.unexplained_findings(findings))
        print(f"quantcheck: {len(entries)} entry program(s), "
              f"{len(bad)} unexplained finding(s), {n_explained} "
              f"explained, {len(stale)} stale explanation(s), "
              f"{len(drift)} baseline drift line(s)"
              + (f" -> wrote {baseline}" if write and baseline else ""))
    return 1 if bad or drift or stale else 0


def run_quantcheck_regression(fmt: str = "text") -> int:
    """The TPL303 regression gate: rebuild the PR 8 pre-fix admit
    program (scale plane not reset on page alloc) and require exactly
    one scale-provenance finding on it and zero on the shipped one."""
    from . import quantcheck as Q

    rep = Q.regression_report()
    if fmt == "json":
        import json

        print(json.dumps(rep, indent=2))
    else:
        for label in ("regression", "shipped"):
            r = rep[label]
            print(f"quantcheck-regression: {r['entry']}: "
                  f"{r['tpl303']} TPL303 finding(s)")
            for m in r["messages"]:
                print(f"  {m}")
        print("quantcheck-regression: "
              + ("OK (pre-fix program fires exactly once, shipped "
                 "program is clean)" if rep["ok"] else
                 "FAIL (expected exactly 1 TPL303 on the pre-fix "
                 "program and 0 on the shipped one)"))
    return 0 if rep["ok"] else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.lint",
        description="tpu-lint: static trace-safety/aliasing/registry "
                    "checks plus abstract op-contract verification "
                    "for the paddle_tpu tree.",
    )
    parser.add_argument("paths", nargs="*", default=["paddle_tpu", "tests"],
                        help="files or directories to lint "
                             "(default: paddle_tpu tests)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text", help="output format")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids/names to run "
                             "(default: all)")
    parser.add_argument("--ignore", default=None,
                        help="comma-separated rule ids/names to skip "
                             "(applied after --select)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--no-default-excludes", action="store_true",
                        help="also lint the seeded-violation fixtures")
    parser.add_argument("--contracts", action="store_true",
                        help="run abstract op-contract verification over "
                             "the dispatch registry instead of lint")
    parser.add_argument("--shardcheck", action="store_true",
                        help="run static sharding/collective verification "
                             "over the registered entry programs instead "
                             "of lint")
    parser.add_argument("--quantcheck", action="store_true",
                        help="run static precision/scale-provenance "
                             "verification over the registered entry "
                             "programs instead of lint")
    parser.add_argument("--quantcheck-regression", action="store_true",
                        help="run the quantcheck TPL303 regression gate "
                             "(the pre-fix scale-leak program must fire "
                             "exactly once; the shipped one not at all)")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="with --contracts/--shardcheck/--quantcheck: "
                             "compare against (or, with --write-baseline, "
                             "regenerate) this JSON baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="with --contracts/--shardcheck/--quantcheck "
                             "and --baseline: write the baseline instead "
                             "of diffing")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_CHECKERS:
            print(f"{cls.rule}  {cls.name:<20} {cls.severity:<8} "
                  f"{cls.description}")
        return 0

    modes = [m for m, on in (("--contracts", args.contracts),
                             ("--shardcheck", args.shardcheck),
                             ("--quantcheck", args.quantcheck),
                             ("--quantcheck-regression",
                              args.quantcheck_regression)) if on]
    if len(modes) > 1:
        print(f"tpu-lint: {' and '.join(modes)} are exclusive",
              file=sys.stderr)
        return 2
    if args.write_baseline and not (
            (args.contracts or args.shardcheck or args.quantcheck)
            and args.baseline):
        print("tpu-lint: --write-baseline requires --contracts, "
              "--shardcheck, or --quantcheck, and --baseline PATH",
              file=sys.stderr)
        return 2
    if args.quantcheck_regression and args.baseline:
        print("tpu-lint: --quantcheck-regression takes no --baseline "
              "(the regression entries are never baselined)",
              file=sys.stderr)
        return 2
    select = ({s.strip() for s in args.select.split(",") if s.strip()}
              if args.select else None)
    ignore = ({s.strip() for s in args.ignore.split(",") if s.strip()}
              if args.ignore else None)
    if args.quantcheck:
        try:
            return run_quantcheck(args.baseline, args.write_baseline,
                                  args.format, select=select,
                                  ignore=ignore)
        except (ImportError, RuntimeError) as e:
            print(f"quantcheck: setup failed: {e}", file=sys.stderr)
            return 2
    if args.quantcheck_regression:
        try:
            return run_quantcheck_regression(args.format)
        except (ImportError, RuntimeError) as e:
            print(f"quantcheck: setup failed: {e}", file=sys.stderr)
            return 2
    if args.contracts:
        try:
            return run_contracts(args.baseline, args.write_baseline,
                                 args.format)
        except ImportError as e:
            print(f"tpu-verify: registry import failed: {e}",
                  file=sys.stderr)
            return 2
    if args.shardcheck:
        try:
            return run_shardcheck(args.baseline, args.write_baseline,
                                  args.format)
        except (ImportError, RuntimeError) as e:
            print(f"shardcheck: setup failed: {e}", file=sys.stderr)
            return 2

    paths = args.paths or ["paddle_tpu", "tests"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpu-lint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    excludes = () if args.no_default_excludes else DEFAULT_EXCLUDES
    findings = run_lint(paths, select=select, excludes=excludes,
                        ignore=ignore)
    render = {"json": render_json, "sarif": render_sarif}.get(
        args.format, render_text)
    print(render(findings))
    return 1 if findings else 0
