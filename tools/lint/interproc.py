"""tpu-lint interprocedural engine: whole-program call-graph taint rules.

The per-file checkers (``checkers.py``) stop at function boundaries: a
helper that calls ``.item()`` escapes TPL001 the moment it is called
*indirectly* from an ``@op``/jit region.  This module closes that gap.

Model
-----
``ProjectIndex`` is built once per lint run (``cli.run_lint`` feeds it
every parsed file) and holds one :class:`FuncInfo` summary per function
definition — plus a synthetic ``<module>`` function per file for
module-level statements.  A summary records only what the rules need:

- direct host-sync sites (``.item()``/``float(tainted)``/``np.asarray``),
- call sites with their dotted target and argument→parameter mapping,
- mesh axes bound by ``shard_map``/``Mesh``/spec calls in the body,
- ``lax.p*`` collective sites and their axis-name literals,
- parameters that flow into ``jnp.asarray`` (the aliasing sink).

``link()`` resolves call targets through each module's import table
(absolute, aliased, and relative imports; ``self.``/``cls.`` methods;
nested defs via the enclosing-scope chain) into a project call graph.
The three rules are then fixpoints over that graph:

TPL101  host sync reachable from an @op/jit trace root through any call
        chain (the transitive closure of the TPL001 taint),
TPL102  a live numpy buffer handed to a helper that (transitively)
        feeds it to ``jnp.asarray`` — aliasing through call chains,
TPL103  a collective reachable from an entry point along a call path on
        which no function binds the collective's mesh axis.

All three report at the *call site* that enters the offending chain, so
a suppression comment lands next to the code a reviewer would change.
Findings name the full chain and the terminal site.

Resolution is best-effort and deliberately first-order: a target that
cannot be resolved statically (dynamic dispatch, getattr, re-export
chains deeper than the import tables) simply contributes no edge.  The
rules only ever report on *resolved* chains, so imprecision costs
recall, never false positives from phantom edges.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field

from .checkers import (
    AsyncAliasing,
    CollectiveSafety,
    HostSyncInTrace,
    _is_shape_query,
    _iter_scope,
    _np_rooted,
    _param_names,
    _trace_kind,
)
from .core import Checker, call_name, dotted_name, names_in, str_constants

__all__ = ["ProjectIndex", "FuncInfo", "CallSite", "INTERPROC_CHECKERS"]

# Source-path anchors: the module name of a file is its path from the
# first anchor component on (``/any/prefix/paddle_tpu/core/tensor.py``
# -> ``paddle_tpu.core.tensor``); un-anchored files get their stem.
_ANCHORS = ("paddle_tpu", "tests", "tools")

# Wrapping calls that turn their first argument into a trace root
# (``jit(f)``, ``to_static(step)``) or bind mesh axes around it
# (``shard_map(f, axis_names=("tp",))``).
_JIT_WRAPPERS = {"jit", "pjit", "to_static"}
_MESH_WRAPPERS = {"shard_map", "pmap", "xmap"}

# Identifiers whose presence in an ``if`` test marks the guarded branch
# as eager-only (``isinstance(x, Tracer)``, ``trace_state_clean()``):
# syncs under such guards never run while tracing.  ``Tensor`` belongs
# here for a structural reason: dispatch unwraps Tensor leaves to raw
# jax arrays before any impl runs, so inside a trace region an
# ``isinstance(x, Tensor)`` branch is unreachable — tracers are never
# Tensor instances.
_TRACE_GUARDS = {"Tracer", "trace_state_clean", "is_tracing", "is_tracer",
                 "Tensor"}


@dataclass
class CallSite:
    """One resolved-or-not call edge out of a function body."""

    node: ast.Call
    target: str                      # dotted name as written at the site
    caller: "FuncInfo"
    is_wrap: bool = False            # shard_map(f, ...)-style wrapping
    wrap_kind: str | None = None     # 'jit' | 'mesh' | 'partial' for wraps
    wrap_axes: set = field(default_factory=set)
    resolved: "FuncInfo | None" = None
    arg_offset: int = 0              # params consumed by partial pre-binding

    def args_to_params(self) -> list:
        """[(callee_param_name, caller_arg_expr)] for positional +
        keyword arguments; empty when the mapping is unreliable
        (*args/**kwargs at the site, unresolved callee)."""
        g = self.resolved
        if g is None:
            return []
        if any(isinstance(a, ast.Starred) for a in self.node.args) or any(
                kw.arg is None for kw in self.node.keywords):
            return []
        params = g.params
        pos_args = self.node.args
        if self.is_wrap and self.wrap_kind == "partial":
            # functools.partial(f, a, b): args after the callable map to
            # f's leading parameters
            pos_args = self.node.args[1:]
        elif self.arg_offset:
            # call THROUGH a stored partial (h = partial(f, a); h(b)):
            # the pre-bound leading params are already consumed
            params = params[self.arg_offset:]
        # bound-method call (x.m(a)): the receiver consumes 'self',
        # which FuncInfo.params already strips — indices line up.
        out = list(zip(params, pos_args))
        by_name = {p: None for p in params}
        for kw in self.node.keywords:
            if kw.arg in by_name:
                out.append((kw.arg, kw.value))
        return out


@dataclass
class FuncInfo:
    """Whole-program summary of one function (or module top level)."""

    qual: str                        # module[.Class].name
    name: str
    module: str
    path: str
    node: ast.AST
    cls: str | None = None           # enclosing class, if a method
    parent: "FuncInfo | None" = None  # enclosing function, if nested
    trace_kind: str | None = None    # 'op' | 'jit' from decorators
    params: list = field(default_factory=list)
    local_defs: dict = field(default_factory=dict)   # nested def name -> FuncInfo
    calls: list = field(default_factory=list)        # [CallSite]
    syncs: list = field(default_factory=list)        # [(node, description)]
    binds: set = field(default_factory=set)          # mesh axes bound in body
    collectives: list = field(default_factory=list)  # [(axis, node, opname)]
    asarray_params: dict = field(default_factory=dict)  # param -> sink pointer
    np_locals: set = field(default_factory=set)      # numpy-buffer locals
    partial_locals: dict = field(default_factory=dict)  # name -> (target, n_bound)

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    @property
    def is_module(self) -> bool:
        return self.name == "<module>"

    def display(self) -> str:
        return self.qual


def module_name_for(path: str) -> tuple[str, bool]:
    """(module dotted name, is_package) for a repo-relative/absolute path."""
    parts = [p for p in path.split("/") if p]
    for i, p in enumerate(parts):
        if p in _ANCHORS:
            parts = parts[i:]
            break
    else:
        parts = parts[-1:]
    is_pkg = False
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
        is_pkg = True
    return ".".join(parts) or path, is_pkg


def _is_trace_guard(test: ast.AST) -> bool:
    ids = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Name):
            ids.add(n.id)
        elif isinstance(n, ast.Attribute):
            ids.add(n.attr)
    return bool(ids & _TRACE_GUARDS)


def _guard_diverts(stmt: ast.If) -> bool:
    """True when the guard body unconditionally leaves the block
    (``if isinstance(o, Tracer): continue``) — the *siblings after it*
    are then eager-only."""
    return bool(stmt.body) and isinstance(
        stmt.body[-1], (ast.Continue, ast.Return, ast.Raise, ast.Break))


def _taint_sources(fn: ast.AST, params: set) -> dict:
    """name -> set of parameters it (transitively) derives from.
    First-order and flow-insensitive, like checkers._propagate_taint,
    but keeps per-parameter attribution for argument mapping."""
    src = {p: {p} for p in params}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [node.target], node.value
            else:
                continue
            if value is None or _is_shape_query(value):
                continue
            feed = set()
            for n in names_in(value):
                feed |= src.get(n, set())
            if not feed:
                continue
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        have = src.setdefault(n.id, set())
                        if not feed <= have:
                            have |= feed
                            changed = True
    return src


class ProjectIndex:
    """Project-wide function summaries + import tables + call graph."""

    def __init__(self):
        self.functions: list[FuncInfo] = []
        self.func_table: dict[str, FuncInfo] = {}
        self._sup = None                               # current file's Suppressions
        self.imports: dict[str, dict[str, str]] = {}   # module -> local -> qual
        self.module_tails: dict[str, str] = {}         # stem -> module (unique)
        self._tail_clash: set[str] = set()
        self.module_funcs: dict[str, dict[str, FuncInfo]] = {}
        self.class_methods: dict[tuple, dict[str, FuncInfo]] = {}
        self.np_attrs: dict[str, set] = {}             # module -> numpy attrs
        self.file_axes: dict[str, set] = {}            # module -> axes bound anywhere in file
        self.module_scope: dict[str, FuncInfo] = {}    # module -> <module> pseudo-fn
        self.jit_wrapped: set[FuncInfo] = set()
        self._linked = False

    # -- construction --------------------------------------------------------

    def add_file(self, ctx) -> None:
        module, is_pkg = module_name_for(ctx.path)
        tail = module.rsplit(".", 1)[-1]
        if tail in self.module_tails and self.module_tails[tail] != module:
            self._tail_clash.add(tail)
            self.module_tails.pop(tail, None)
        elif tail not in self._tail_clash:
            self.module_tails[tail] = module
        self.imports.setdefault(module, {})
        self._harvest_imports(ctx.tree, module, is_pkg)
        self.np_attrs[module] = self._harvest_np_attrs(ctx.tree)
        self.file_axes[module] = self._harvest_file_axes(ctx.tree)
        # module-level pseudo-function, then every def (incl. nested)
        self._sup = ctx.suppressions
        top = FuncInfo(qual=f"{module}.<module>", name="<module>",
                       module=module, path=ctx.path, node=ctx.tree)
        self._summarize(top)
        self.functions.append(top)
        self.module_scope[module] = top
        self._walk_defs(ctx.tree, module, ctx.path, cls=None, parent=None)
        self._sup = None
        self._linked = False

    def _walk_defs(self, node: ast.AST, module: str, path: str,
                   cls: str | None, parent: FuncInfo | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (f"{module}.{cls}.{child.name}" if cls
                        else f"{module}.{child.name}")
                info = FuncInfo(
                    qual=qual, name=child.name, module=module, path=path,
                    node=child, cls=cls, parent=parent,
                    trace_kind=_trace_kind(child),
                    params=self._positional_params(child),
                )
                self._summarize(info)
                self.functions.append(info)
                self.func_table.setdefault(qual, info)
                if parent is None and cls is None:
                    self.module_funcs.setdefault(module, {})[child.name] = info
                if cls is not None and parent is None:
                    self.class_methods.setdefault(
                        (module, cls), {})[child.name] = info
                if parent is not None:
                    parent.local_defs[child.name] = info
                self._walk_defs(child, module, path, cls=None, parent=info)
            elif isinstance(child, ast.ClassDef):
                self._walk_defs(child, module, path, cls=child.name,
                                parent=parent)
            else:
                self._walk_defs(child, module, path, cls=cls, parent=parent)

    @staticmethod
    def _positional_params(fn: ast.FunctionDef) -> list:
        a = fn.args
        names = [p.arg for p in a.posonlyargs + a.args]
        return [n for n in names if n not in ("self", "cls")]

    def _harvest_imports(self, tree: ast.AST, module: str, is_pkg: bool):
        table = self.imports[module]
        package = module if is_pkg else module.rsplit(".", 1)[0] \
            if "." in module else ""
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        table[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        table[root] = root
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = package.split(".") if package else []
                    cut = node.level - 1
                    if cut:
                        up = up[:-cut] if cut <= len(up) else []
                    base = ".".join(up + ([node.module] if node.module
                                          else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    table[local] = f"{base}.{alias.name}" if base \
                        else alias.name

    @staticmethod
    def _harvest_np_attrs(tree: ast.AST) -> set:
        attrs = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call) \
                    and _np_rooted(call_name(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        attrs.add(t.attr)
                    elif isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Attribute):
                        attrs.add(t.value.attr)
        return attrs

    @staticmethod
    def _harvest_file_axes(tree: ast.AST) -> set:
        bound = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            tail = call_name(node).rsplit(".", 1)[-1]
            if tail in CollectiveSafety.BINDERS:
                bound |= str_constants(node)
            else:
                for kw in node.keywords:
                    if kw.arg in CollectiveSafety.BINDER_KWARGS:
                        bound |= str_constants(kw.value)
        return bound

    # -- per-function summaries ----------------------------------------------

    def _sink_suppressed(self, node: ast.AST, rule: str, name: str) -> bool:
        """A ``tpu-lint: disable=<rule>`` comment on a *sink* line (the
        host sync, the jnp.asarray, the collective) removes that hazard
        from the index entirely — one rationale next to the helper kills
        every chain through it, instead of one suppression per caller."""
        if self._sup is None:
            return False
        from .core import Finding

        return self._sup.matches(Finding(
            rule, name, "error", "", getattr(node, "lineno", 1), 0, "",
            end_line=getattr(node, "end_lineno", 0) or 0))

    def _summarize(self, f: FuncInfo) -> None:
        self._collect_syncs(f)
        self._collect_calls_binds_collectives(f)
        self._collect_asarray_flow(f)

    @staticmethod
    def _taint_seeds(f: FuncInfo) -> set:
        """Parameters that may carry traced arrays — scalar-annotated
        parameters (``bit_length: int``) are static config, exactly as
        TPL001 treats them."""
        if isinstance(f.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return _param_names(f.node)
        return set()

    def _collect_syncs(self, f: FuncInfo) -> None:
        """Direct host-sync sites, skipping eager-only guarded branches."""
        tainted = _taint_sources(f.node, self._taint_seeds(f))

        def scan_block(stmts, guarded):
            for stmt in stmts:
                if isinstance(stmt, ast.If) and _is_trace_guard(stmt.test):
                    scan_block(stmt.orelse, guarded)
                    if _guard_diverts(stmt):
                        guarded = True  # siblings below never see tracers
                    continue
                scan_stmt(stmt, guarded)

        def scan_stmt(stmt, guarded):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            blocks = [(getattr(stmt, "body", None)),
                      (getattr(stmt, "orelse", None)),
                      (getattr(stmt, "finalbody", None))]
            has_blocks = any(isinstance(b, list) for b in blocks)
            if has_blocks:
                if not guarded:
                    for expr_field in ("test", "iter"):
                        sub = getattr(stmt, expr_field, None)
                        if isinstance(sub, ast.AST):
                            self._sync_sites_in(sub, f, tainted)
                for b in blocks:
                    if isinstance(b, list):
                        scan_block(b, guarded)
                for h in getattr(stmt, "handlers", []):
                    scan_block(h.body, guarded)
            elif not guarded:
                self._sync_sites_in(stmt, f, tainted)

        body = (f.node.body if hasattr(f.node, "body")
                and isinstance(f.node.body, list) else [])
        scan_block(body, False)

    @staticmethod
    def _walk_no_defs(node: ast.AST):
        """ast.walk that does not descend into nested defs/lambdas."""
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            for c in ast.iter_child_nodes(n):
                if not isinstance(c, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    stack.append(c)

    def _sync_sites_in(self, node: ast.AST, f: FuncInfo, tainted: dict):
        for n in self._walk_no_defs(node):
            if not isinstance(n, ast.Call):
                continue
            if self._sink_suppressed(n, "TPL101", "host-sync-transitive"):
                continue
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr in HostSyncInTrace.SYNC_METHODS
                    and not n.args):
                f.syncs.append((n, f".{n.func.attr}()"))
                continue
            cname = call_name(n)
            if cname in HostSyncInTrace.NP_CONVERTERS and n.args:
                feed = set()
                for nm in names_in(n.args[0]):
                    feed |= tainted.get(nm, set())
                if feed:
                    f.syncs.append((n, f"{cname}() over "
                                       f"'{sorted(feed)[0]}'"))
            elif (cname in HostSyncInTrace.CONCRETIZERS
                    and len(n.args) == 1
                    and not _is_shape_query(n.args[0])):
                feed = set()
                for nm in names_in(n.args[0]):
                    feed |= tainted.get(nm, set())
                if feed:
                    f.syncs.append((n, f"{cname}() over "
                                       f"'{sorted(feed)[0]}'"))

    def _collect_calls_binds_collectives(self, f: FuncInfo) -> None:
        for node in _iter_scope(f.node):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            tail = cname.rsplit(".", 1)[-1] if cname else ""
            # mesh-axis binders (shard_map/Mesh/specs) bind for this body
            if tail in CollectiveSafety.BINDERS:
                f.binds |= str_constants(node)
            else:
                for kw in node.keywords:
                    if kw.arg in CollectiveSafety.BINDER_KWARGS:
                        f.binds |= str_constants(kw.value)
            # collectives
            root, _, ctail = cname.rpartition(".")
            if ctail in CollectiveSafety.COLLECTIVES and root in (
                    "lax", "jax.lax"):
                axis_pos = 0 if ctail == "axis_index" else 1
                axis_arg = None
                if len(node.args) > axis_pos:
                    axis_arg = node.args[axis_pos]
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis"):
                        axis_arg = kw.value
                if axis_arg is not None and not self._sink_suppressed(
                        node, "TPL103", "collective-unbound-path"):
                    for ax in sorted(str_constants(axis_arg)):
                        f.collectives.append((ax, node, ctail))
            # wrapping: shard_map(g, ...) / jit(g) with a named first arg
            if tail in (_JIT_WRAPPERS | _MESH_WRAPPERS) and node.args:
                wrapped = dotted_name(node.args[0])
                if wrapped:
                    f.calls.append(CallSite(
                        node=node, target=wrapped, caller=f, is_wrap=True,
                        wrap_kind=("jit" if tail in _JIT_WRAPPERS
                                   else "mesh"),
                        wrap_axes=(str_constants(node)
                                   if tail in _MESH_WRAPPERS else set()),
                    ))
            # functools.partial(g, ...) pre-binds arguments; the
            # CREATION site is the call edge, because the value may be
            # stored (a dict slot, a work queue — the disagg router's
            # job["wire"]) and invoked where no static target is
            # visible. Reachability for TPL101-103 and the typestate
            # rules must not depend on seeing the eventual invocation.
            if tail == "partial" and node.args:
                wrapped = dotted_name(node.args[0])
                if wrapped:
                    f.calls.append(CallSite(
                        node=node, target=wrapped, caller=f, is_wrap=True,
                        wrap_kind="partial"))
            if cname:
                f.calls.append(CallSite(node=node, target=cname, caller=f))
            # numpy buffer locals (for TPL102 caller-side detection)
        for node in _iter_scope(f.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            if _np_rooted(call_name(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        f.np_locals.add(t.id)
            # h = functools.partial(g, a): direct calls through 'h'
            # resolve to g with the pre-bound params consumed
            vtail = call_name(node.value).rsplit(".", 1)[-1]
            if vtail == "partial" and node.value.args:
                wrapped = dotted_name(node.value.args[0])
                if wrapped:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            f.partial_locals[t.id] = (
                                wrapped, len(node.value.args) - 1)

    def _collect_asarray_flow(self, f: FuncInfo) -> None:
        """Parameters that flow directly into jnp.asarray in this body."""
        if not f.params:
            return
        tainted = _taint_sources(f.node, set(f.params))
        for node in _iter_scope(f.node):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in AsyncAliasing.ASARRAY
                    and node.args):
                continue
            if self._sink_suppressed(node, "TPL102",
                                     "async-aliasing-transitive"):
                continue
            root, _ = AsyncAliasing._alias_chain(node.args[0])
            if root is None:
                continue
            for p in tainted.get(root, set()):
                f.asarray_params.setdefault(p, ("direct", node))

    # -- linking -------------------------------------------------------------

    def link(self) -> None:
        if self._linked:
            return
        for f in self.functions:
            for site in f.calls:
                site.resolved = self._resolve(site)
                if (site.is_wrap and site.wrap_kind == "jit"
                        and site.resolved is not None):
                    self.jit_wrapped.add(site.resolved)
        self._linked = True

    def _resolve(self, site: CallSite,
                 _hops: frozenset = frozenset()) -> FuncInfo | None:
        parts = site.target.split(".")
        caller = site.caller
        # self.m() / cls.m() within a class body
        if parts[0] in ("self", "cls") and caller.cls and len(parts) == 2:
            return self.class_methods.get(
                (caller.module, caller.cls), {}).get(parts[1])
        pkg = caller.module.rpartition(".")[0]
        if len(parts) == 1:
            name = parts[0]
            scopes = []
            scope = caller
            while scope is not None:            # nested defs, innermost out
                scopes.append(scope)
                scope = scope.parent
            # module-level partials (send = functools.partial(f, tag))
            # live on the <module> pseudo-function, which is nobody's
            # parent — append it as the outermost scope
            mod_top = self.module_scope.get(caller.module)
            if mod_top is not None and mod_top not in scopes:
                scopes.append(mod_top)
            for scope in scopes:
                if name in scope.local_defs:
                    return scope.local_defs[name]
                if name in scope.partial_locals:
                    # cycle guard: re-binding idioms (f = partial(f, x))
                    # would otherwise hop forever
                    hop = (id(scope), name)
                    if hop in _hops:
                        return None
                    target, n_bound = scope.partial_locals[name]
                    site.arg_offset = n_bound
                    return self._resolve(
                        CallSite(node=site.node, target=target,
                                 caller=scope),
                        _hops | {hop})
            local = self.module_funcs.get(caller.module, {}).get(name)
            if local is not None:
                return local
            qual = self.imports.get(caller.module, {}).get(name)
            return self._resolve_qual(qual, pkg) if qual else None
        first = self.imports.get(caller.module, {}).get(parts[0])
        if first:
            return self._resolve_qual(".".join([first] + parts[1:]), pkg)
        return self._resolve_qual(site.target, pkg)

    def _resolve_qual(self, qual: str, caller_pkg: str,
                      _seen=None) -> FuncInfo | None:
        if _seen is None:
            _seen = set()
        if qual in _seen:
            return None
        _seen.add(qual)
        hit = self.func_table.get(qual)
        if hit is not None:
            return hit
        parts = qual.split(".")
        # re-export hop: module M with `from .x import f` makes M.f an
        # alias for M.x.f — follow one import-table indirection (the
        # next hop resolves relative to M's package)
        mod, _, name = qual.rpartition(".")
        target = self.imports.get(mod, {}).get(name)
        if target and target != qual:
            return self._resolve_qual(target, mod.rpartition(".")[0],
                                      _seen)
        # un-anchored module spelling (fixtures import a sibling by
        # stem). Sibling-package restriction on purpose: python only
        # resolves bare imports to tree files when they share the
        # directory — without it, `import math` in ops/ would false-edge
        # stdlib calls into paddle_tpu.ops.math.
        for i in range(len(parts) - 1, 0, -1):
            stem = parts[i - 1]
            real = self.module_tails.get(stem)
            if (real and real != ".".join(parts[:i])
                    and real.rpartition(".")[0] == caller_pkg):
                return self._resolve_qual(
                    ".".join([real] + parts[i:]), caller_pkg, _seen)
        return None

    # -- graph queries shared by the rules -----------------------------------

    def reverse_edges(self) -> dict:
        rev: dict[FuncInfo, list] = {}
        for f in self.functions:
            for site in f.calls:
                if site.resolved is not None and site.resolved is not f:
                    rev.setdefault(site.resolved, []).append((f, site))
        return rev

    def trace_roots(self) -> list:
        return [f for f in self.functions
                if f.trace_kind or f in self.jit_wrapped]


# -- rule base ---------------------------------------------------------------

class InterprocChecker(Checker):
    """Base for whole-program rules: ``cli.run_lint`` injects a shared
    :class:`ProjectIndex` as ``self.project``; per-file ``check`` is a
    no-op and all reporting happens in ``finalize``."""

    needs_project = True

    def __init__(self):
        super().__init__()
        self.project: ProjectIndex | None = None

    def check(self, ctx) -> None:          # summaries are built centrally
        return None


def _chain_names(chain: list) -> str:
    return " -> ".join(f.name for f in chain)


# -- TPL101: transitive host sync under trace --------------------------------

class TransitiveHostSync(InterprocChecker):
    """A trace root (``@op`` lowering, jit/to_static function) calling a
    helper that — through any chain — performs a host sync breaks
    whole-program capture exactly like the direct TPL001 case, but the
    per-file rule cannot see it."""

    rule = "TPL101"
    name = "host-sync-transitive"
    description = ("host-synchronizing helper reachable from an @op/jit "
                   "region through a call chain")

    def finalize(self):
        p = self.project
        if p is None:
            return
        p.link()
        rev = p.reverse_edges()
        # BFS up from every function with a direct sync; next_hop[f]
        # remembers the first edge of a shortest chain f -> ... -> sync
        next_hop: dict[FuncInfo, tuple] = {}
        queue = deque(f for f in p.functions if f.syncs)
        seen = set(queue)
        while queue:
            g = queue.popleft()
            for caller, site in rev.get(g, []):
                if caller not in seen:
                    seen.add(caller)
                    next_hop[caller] = (site, g)
                    queue.append(caller)
        for root in p.trace_roots():
            where = ("@op lowering" if root.trace_kind == "op"
                     else "jit/to_static region")
            for site in root.calls:
                g = site.resolved
                if g is None or g is root or g not in seen:
                    continue
                chain, cur = [root, g], g
                while not cur.syncs:
                    _, cur = next_hop[cur]
                    chain.append(cur)
                node, what = cur.syncs[0]
                self.report(
                    site.node,
                    f"call chain {_chain_names(chain)} from {where} "
                    f"'{root.name}' reaches a host sync: {what} at "
                    f"{cur.path}:{node.lineno} forces a device->host "
                    "sync under tracing",
                    path=root.path)


# -- TPL102: aliasing through helper call chains -----------------------------

class TransitiveAsarrayAlias(InterprocChecker):
    """A live numpy buffer passed to a helper whose parameter
    (transitively) reaches ``jnp.asarray`` aliases zero-copy into async
    dispatch just like the direct TPL002 case.  Same strictness model:
    always flagged under the async-by-construction paths and for
    attribute-held buffers, elsewhere only when the buffer is mutated
    after the handoff."""

    rule = "TPL102"
    name = "async-aliasing-transitive"
    description = ("numpy buffer reaching jnp.asarray through a helper "
                   "call chain may alias into async dispatch")

    def finalize(self):
        p = self.project
        if p is None:
            return
        p.link()
        # fixpoint: param -> sink pointer, propagated through call sites
        flow = {f: dict(f.asarray_params) for f in p.functions
                if f.asarray_params}
        changed = True
        while changed:
            changed = False
            for f in p.functions:
                for site in f.calls:
                    g = site.resolved
                    # partial wraps DO hand arguments over (the buffer is
                    # captured at creation time) — only jit/mesh wraps
                    # pass a callable, not data
                    if g is None or g not in flow or (
                            site.is_wrap and site.wrap_kind != "partial"):
                        continue
                    for g_param, expr in site.args_to_params():
                        if g_param not in flow[g]:
                            continue
                        root, _ = AsyncAliasing._alias_chain(expr)
                        if (root in f.params
                                and root not in flow.setdefault(f, {})):
                            flow[f][root] = (site, g, g_param)
                            changed = True
        for f in p.functions:
            strict = any(s in f.path for s in AsyncAliasing.STRICT_PATHS)
            for site in f.calls:
                g = site.resolved
                if g is None or g not in flow or (
                        site.is_wrap and site.wrap_kind != "partial"):
                    continue
                for g_param, expr in site.args_to_params():
                    if g_param not in flow[g]:
                        continue
                    root, attrs = AsyncAliasing._alias_chain(expr)
                    if root is None:
                        continue
                    held = bool(set(attrs) & p.np_attrs.get(f.module,
                                                            set()))
                    local = root in f.np_locals
                    if not held and not local:
                        continue
                    if not strict and not held and not (
                            AsyncAliasing._mutated_after(
                                f.node, root, site.node.lineno)):
                        continue
                    what = (".".join([root] + list(reversed(attrs)))
                            if held else root)
                    chain = self._chain(flow, g, g_param)
                    self.report(
                        site.node,
                        f"numpy buffer '{what}' handed to "
                        f"'{g.name}({g_param}=...)' reaches jnp.asarray "
                        f"via {chain}; it may alias zero-copy into an "
                        "async dispatched program — copy with jnp.array "
                        "or justify with a suppression",
                        path=f.path)

    @staticmethod
    def _chain(flow, g, g_param) -> str:
        hops = [g.name]
        ptr = flow[g][g_param]
        while ptr[0] != "direct":
            _, g, g_param = ptr
            hops.append(g.name)
            ptr = flow[g][g_param]
        sink = ptr[1]
        return (" -> ".join(hops)
                + f" -> jnp.asarray at line {sink.lineno}")


# -- TPL103: collectives on call paths with no axis binding ------------------

class UnboundCollectivePath(InterprocChecker):
    """TPL005 accepts a collective when *any* site in the same file binds
    its axis — which is exactly how helpers get reused from a code path
    that never enters the shard_map: the file looks safe, the new call
    path traces with an unbound axis name and dies deep inside XLA.
    This rule walks caller chains: an entry point (a function nobody in
    the project calls, or module-level code) whose file binds nothing
    for the axis, reaching a collective with no binder anywhere along
    the chain, is reported at the entry's call site."""

    rule = "TPL103"
    name = "collective-unbound-path"
    description = ("collective reachable through a call chain on which "
                   "no caller binds the mesh axis")

    def finalize(self):
        p = self.project
        if p is None:
            return
        p.link()
        # need[f]: axis -> pointer into the chain towards the collective
        need: dict[FuncInfo, dict] = {}
        for f in p.functions:
            for ax, node, ctail in f.collectives:
                if ax not in f.binds:
                    need.setdefault(f, {})[ax] = ("coll", node, ctail, f)
        changed = True
        while changed:
            changed = False
            for f in p.functions:
                for site in f.calls:
                    g = site.resolved
                    if g is None or g is f or g not in need:
                        continue
                    for ax in need[g]:
                        if ax in f.binds or ax in site.wrap_axes:
                            continue
                        if ax not in need.setdefault(f, {}):
                            need[f][ax] = ("call", site, g)
                            changed = True
        has_callers = set()
        for f in p.functions:
            for site in f.calls:
                if site.resolved is not None and site.resolved is not f:
                    has_callers.add(site.resolved)
        for f in p.functions:
            if f in has_callers and not f.is_module:
                continue                      # not an entry point
            for ax, ptr in sorted(need.get(f, {}).items()):
                if ptr[0] != "call":
                    continue  # the entry owns the collective: TPL005 turf
                if ax in p.file_axes.get(f.module, set()):
                    continue  # entry's own file binds it somewhere
                _, site, g = ptr
                chain = [f]
                cur = ptr
                while cur[0] == "call":
                    chain.append(cur[2])
                    cur = need[cur[2]][ax]
                _, node, ctail, owner = cur
                self.report(
                    site.node,
                    f"lax.{ctail}('{ax}') at {owner.path}:{node.lineno} "
                    f"is reachable via {_chain_names(chain)} from entry "
                    f"'{f.display()}' with no shard_map/Mesh binding "
                    f"of axis '{ax}' anywhere on the path",
                    path=f.path)


INTERPROC_CHECKERS = [
    TransitiveHostSync,
    TransitiveAsarrayAlias,
    UnboundCollectivePath,
]
