"""tpu-lint typestate rules: the disagg wire protocol, verified statically.

The prefill->decode wire (inference/fleet/, inference/serving.py) is a
three-phase protocol over the page ledger's ``in_flight`` class:

  ``begin_adopt``  allocates pages and stages a shipment (ledger class
                   in_flight) — the handle it returns OWNS those pages;
  ``commit_adopt`` publishes them into the prefix cache (or defers the
                   device scatter to ``_flush_commits`` under
                   ``wire_overlap``);
  ``abort_adopt``  rolls the staging back to the free list.

Every dynamic smoke (disagg, fleet, chaos) exercises one interleaving;
these rules verify the protocol on **all paths**, interprocedurally, on
the :class:`~tools.lint.interproc.ProjectIndex`:

TPL211  adopt-without-resolve     every ``begin_adopt`` handle reaches
        exactly one of ``commit_adopt``/``abort_adopt`` (or escapes to
        the caller / a resolving helper) on every path — a path that
        drops a staged handle leaks in_flight pages forever; resolving
        twice double-releases.
TPL212  staged-flush-barrier      in a class with deferred commits
        (defines ``_flush_commits``), no method may dispatch a program
        over the page arrays or snapshot them for export without the
        flush barrier first — a staged page read before its flush sees
        stale bytes (exactly the ordering ``_dispatch_unified`` /
        ``stage_request_pages`` / ``export_request_pages`` maintain).
TPL213  release-before-guard      releasing scheduler-owned pages
        (``owned`` / ``_deferred_free``) is only safe after the
        in-flight-program guard — an unguarded release hands pages back
        while a dispatched program may still write them.

Like the TPL10x family, resolution is first-order and best-effort:
unresolvable dynamic dispatch contributes no edge, so imprecision costs
recall, never phantom findings.  Functions in ``tests.*`` modules are
exempt (tests intentionally drive partial protocols to probe recovery) —
except the seeded-violation fixtures under ``lint_fixtures``, which are
exactly the files that must fire.
"""

from __future__ import annotations

import ast

from .core import names_in
from .interproc import FuncInfo, InterprocChecker

__all__ = ["TYPESTATE_CHECKERS", "AdoptProtocol", "StagedFlushBarrier",
           "ReleaseBeforeGuard"]

_BEGIN = "begin_adopt"
_RESOLVE_TAILS = {"commit_adopt", "abort_adopt"}
_PAGE_ATTRS = {"k_pages", "v_pages", "k_scales", "v_scales"}
_GUARD_IDS = {"_inflight", "defer"}
_OWNED_ARGS = {"owned", "_deferred_free"}

# handle states
_STAGED = "staged"
_DONE = "done"


def _in_tests(f: FuncInfo) -> bool:
    # seeded-violation fixtures anchor under tests/ too — they must fire
    if "lint_fixtures" in f.module:
        return False
    return f.module == "tests" or f.module.startswith("tests.")


def _idents(node: ast.AST) -> set:
    """Name ids AND attribute names in an expression (names_in sees only
    bare Names — ``self._deferred_free`` must count as a mention too)."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _call_tail(node: ast.Call) -> str:
    from .core import call_name

    cname = call_name(node)
    return cname.rsplit(".", 1)[-1] if cname else ""


# ---------------------------------------------------------------------------
# TPL211: begin_adopt handles resolve exactly once on every path
# ---------------------------------------------------------------------------

class AdoptProtocol(InterprocChecker):
    """Path-sensitive handle tracking per function body, with an
    interprocedural resolver fixpoint: a helper that commits/aborts a
    parameter (directly or transitively) resolves any handle passed into
    that parameter."""

    rule = "TPL211"
    name = "adopt-without-resolve"
    severity = "error"
    description = ("begin_adopt handle must reach exactly one of "
                   "commit_adopt/abort_adopt on every path")

    def finalize(self):
        p = self.project
        if p is None:
            return
        p.link()
        resolvers = self._resolver_params(p)
        for f in p.functions:
            if _in_tests(f) or f.is_module:
                continue
            if f.name == _BEGIN:
                continue          # the protocol's own implementation
            self._check_function(f, resolvers)

    # -- interprocedural half ------------------------------------------------

    @staticmethod
    def _resolver_params(p) -> dict:
        """FuncInfo -> set of parameter names whose value the function
        resolves (commits/aborts/hands to another resolver)."""
        res: dict = {}
        changed = True
        while changed:
            changed = False
            for f in p.functions:
                for site in f.calls:
                    tail = site.target.rsplit(".", 1)[-1]
                    if tail in _RESOLVE_TAILS and site.node.args:
                        for nm in names_in(site.node.args[0]):
                            if nm in f.params and nm not in res.setdefault(
                                    f, set()):
                                res[f].add(nm)
                                changed = True
                    g = site.resolved
                    if g is None or g not in res or site.is_wrap:
                        continue
                    for g_param, expr in site.args_to_params():
                        if g_param not in res[g]:
                            continue
                        for nm in names_in(expr):
                            if nm in f.params and nm not in res.setdefault(
                                    f, set()):
                                res[f].add(nm)
                                changed = True
        return res

    # -- intraprocedural half ------------------------------------------------

    def _check_function(self, f: FuncInfo, resolvers: dict):
        body = getattr(f.node, "body", None)
        if not isinstance(body, list):
            return
        self._f = f
        self._resolvers = resolvers
        self._pending_exits = []
        # falls off the end of the function body with a staged handle =
        # leak; Return paths check themselves, Raise paths hand cleanup
        # to the caller (the adopt_pages except/abort shape) and are
        # deliberately exempt
        for _, state in self._block(body, {}):
            self._check_leaks(state)

    def _report_leak(self, begin_node):
        if getattr(begin_node, "_tpl211_reported", False):
            return
        begin_node._tpl211_reported = True
        self.report(
            begin_node,
            "begin_adopt handle may escape without commit_adopt/"
            "abort_adopt on some path — staged pages stay in the "
            "in_flight ledger class forever; resolve the handle on "
            "every path (the adopt_pages try/commit/except/abort shape)",
            path=self._f.path)

    def _check_leaks(self, state: dict):
        for var, (st, node) in state.items():
            if st == _STAGED:
                self._report_leak(node)

    def _resolving_call(self, call: ast.Call) -> bool:
        tail = _call_tail(call)
        if tail in _RESOLVE_TAILS:
            return True
        # a resolved callee that resolves the corresponding parameter
        for site in self._f.calls:
            if site.node is call and site.resolved is not None:
                res = self._resolvers.get(site.resolved, set())
                if res:
                    return True
        return False

    def _resolved_vars(self, call: ast.Call, state: dict) -> list:
        """Handle vars this call resolves."""
        tail = _call_tail(call)
        out = []
        if tail in _RESOLVE_TAILS and call.args:
            out += [nm for nm in names_in(call.args[0]) if nm in state]
        for site in self._f.calls:
            if site.node is not call or site.resolved is None:
                continue
            res = self._resolvers.get(site.resolved, set())
            for g_param, expr in site.args_to_params():
                if g_param in res:
                    out += [nm for nm in names_in(expr) if nm in state]
        return out

    def _scan_calls(self, node: ast.AST, state: dict):
        """Process begin/resolve calls inside one simple statement or
        expression, in source order."""
        calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
        calls.sort(key=lambda n: (n.lineno, n.col_offset))
        for call in calls:
            for var in self._resolved_vars(call, state):
                st, bn = state[var]
                if st == _DONE:
                    self.report(
                        call,
                        f"handle '{var}' resolved twice (second "
                        "commit_adopt/abort_adopt here) — the staged "
                        "pages double-release; every path must resolve "
                        "exactly once",
                        path=self._f.path)
                state[var] = (_DONE, bn)

    def _begin_target(self, stmt: ast.stmt):
        """(var, call) when the statement binds a begin_adopt result to
        a simple name; (None, call) when a begin result is discarded."""
        calls = [n for n in ast.walk(stmt) if isinstance(n, ast.Call)
                 and _call_tail(n) == _BEGIN]
        if not calls:
            return None, None
        call = calls[0]
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.value is call):
            return stmt.targets[0].id, call
        if isinstance(stmt, (ast.Return,)):
            return "<returned>", call      # handed to the caller
        return None, call

    @staticmethod
    def _none_test(test: ast.AST, state: dict):
        """('is_none'|'not_none', var) for ``h is None`` narrowing."""
        if (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.left, ast.Name)
                and test.left.id in state
                and len(test.comparators) == 1
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None):
            if isinstance(test.ops[0], ast.Is):
                return "is_none", test.left.id
            if isinstance(test.ops[0], ast.IsNot):
                return "not_none", test.left.id
        return None, None

    @staticmethod
    def _state_key(state: dict):
        return frozenset((v, st, id(n)) for v, (st, n) in state.items())

    @classmethod
    def _dedupe(cls, outs: list) -> list:
        """Collapse (kind, state) pairs with identical abstract states —
        without this, every branch statement doubles the path list and
        handle-free functions explode exponentially."""
        seen = set()
        uniq = []
        for kind, s in outs:
            key = (kind, cls._state_key(s))
            if key not in seen:
                seen.add(key)
                uniq.append((kind, s))
        return uniq

    def _block(self, stmts: list, state: dict) -> list:
        """Abstractly execute a statement list. ``state`` maps handle
        var -> (state, begin node). Returns [(exit_kind, state)] where
        exit_kind is 'fall' | 'return' | 'break' | 'continue' | 'raise';
        'fall' means execution reaches the end of the list."""
        states = [dict(state)]
        for stmt in stmts:
            new_states = []
            exited = []
            for st in states:
                outs = self._stmt(stmt, st)
                for kind, s in outs:
                    if kind == "fall":
                        new_states.append(s)
                    else:
                        exited.append((kind, s))
            # non-fall exits leave the block immediately
            self._pending_exits.extend(exited)
            states = [s for _, s in self._dedupe(
                ("fall", s) for s in new_states)]
            if not states:
                return []
        return [("fall", s) for s in states]

    def _run_block(self, stmts: list, state: dict) -> list:
        """_block plus collection of inner exits."""
        saved, self._pending_exits = getattr(self, "_pending_exits", []), []
        falls = self._block(stmts, state)
        exits = self._dedupe(self._pending_exits + falls)
        self._pending_exits = saved
        return exits

    def _stmt(self, stmt: ast.stmt, state: dict) -> list:
        state = dict(state)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return [("fall", state)]
        if isinstance(stmt, ast.If):
            kind, var = self._none_test(stmt.test, state)
            self._scan_calls(stmt.test, state)
            then_state, else_state = dict(state), dict(state)
            if kind == "is_none":
                then_state.pop(var, None)     # no handle on the None path
            elif kind == "not_none":
                else_state.pop(var, None)
            outs = self._run_block(stmt.body, then_state)
            outs += self._run_block(stmt.orelse, else_state)
            return self._split(outs)
        if isinstance(stmt, ast.Try):
            outs = self._run_block(stmt.body, state)
            # a handler can run with the state from ANY point in the try
            # body — entry state is the most-staged approximation
            for h in stmt.handlers:
                outs += self._run_block(h.body, dict(state))
            outs2 = []
            for kind, s in outs:
                if stmt.finalbody:
                    for k2, s2 in self._run_block(stmt.finalbody, s):
                        outs2.append((kind if k2 == "fall" else k2, s2))
                else:
                    outs2.append((kind, s))
            if stmt.orelse:
                extra = []
                for kind, s in outs2:
                    if kind == "fall":
                        extra += self._run_block(stmt.orelse, s)
                    else:
                        extra.append((kind, s))
                outs2 = extra
            return self._split(outs2)
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            test = getattr(stmt, "test", None) or getattr(stmt, "iter",
                                                          None)
            if test is not None:
                self._scan_calls(test, state)
            outs = self._run_block(stmt.body, dict(state))
            results = [("fall", dict(state))]       # zero iterations
            for kind, s in outs:
                if kind in ("break", "continue", "fall"):
                    results.append(("fall", s))
                else:
                    results.append((kind, s))
            results += self._run_block(stmt.orelse, dict(state)) \
                if stmt.orelse else []
            return self._split(results)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_calls(item.context_expr, state)
            return self._split(self._run_block(stmt.body, state))
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_calls(stmt.value, state)
                for nm in names_in(stmt.value):
                    if nm in state:               # handle escapes upward:
                        st, bn = state[nm]        # caller owns it now
                        state[nm] = (_DONE, bn)
            self._check_leaks(state)
            return [("return", state)]
        if isinstance(stmt, ast.Raise):
            # exception paths hand cleanup to the caller's except/abort
            return [("raise", state)]
        if isinstance(stmt, ast.Break):
            return [("break", state)]
        if isinstance(stmt, ast.Continue):
            return [("continue", state)]
        # simple statement: begin-binding, then resolves, in order
        var, begin = self._begin_target(stmt)
        self._scan_calls(stmt, state)
        if begin is not None:
            if var is None:
                self.report(
                    begin,
                    "begin_adopt result discarded — the handle owns the "
                    "staged pages; bind it and resolve it with "
                    "commit_adopt/abort_adopt",
                    path=self._f.path)
            elif var == "<returned>":
                pass                               # escapes to the caller
            else:
                state[var] = (_STAGED, begin)
        return [("fall", state)]

    def _split(self, outs: list) -> list:
        """Route non-fall exits to _pending_exits, keep falls local."""
        falls = []
        for kind, s in outs:
            if kind == "fall":
                falls.append(("fall", s))
            elif kind in ("return", "raise"):
                self._pending_exits.append((kind, s))
            else:
                falls.append((kind, s))    # break/continue bubble up one
        return falls


# ---------------------------------------------------------------------------
# TPL212: no staged-page read before the flush barrier
# ---------------------------------------------------------------------------

class StagedFlushBarrier(InterprocChecker):
    """In classes with deferred adoption commits (they define
    ``_flush_commits``), a method that dispatches the unified program or
    gathers pages for export must flush first — otherwise it reads pages
    whose committed bytes are still host-side in ``_commit_pending``."""

    rule = "TPL212"
    name = "staged-flush-barrier"
    severity = "error"
    description = ("page-array read (dispatch/export) without a prior "
                   "_flush_commits barrier in a deferred-commit class")

    # methods that ARE the commit/flush machinery (they write, not read)
    _EXEMPT = {"_flush_commits", "commit_adopt", "__init__"}
    _READ_CALL_TAILS = {"_unified", "wire_gather_pages"}

    def finalize(self):
        p = self.project
        if p is None:
            return
        p.link()
        for (module, cls), methods in sorted(p.class_methods.items()):
            if ((module == "tests" or module.startswith("tests."))
                    and "lint_fixtures" not in module):
                continue
            if "_flush_commits" not in methods:
                continue
            for name, m in sorted(methods.items()):
                if name in self._EXEMPT:
                    continue
                read = self._first_read(m)
                if read is None:
                    continue
                node, what = read
                if self._flushes_before(m, node.lineno):
                    continue
                self.report(
                    node,
                    f"{cls}.{name} reads staged pages ({what}) with no "
                    "_flush_commits barrier earlier in the method — a "
                    "deferred adoption commit may still be pending, so "
                    "the program/export sees stale page bytes; flush "
                    "first (the _dispatch_unified preamble)",
                    path=m.path)

    def _first_read(self, m: FuncInfo):
        """Earliest staged-state read: a ``self._unified(...)`` dispatch,
        a ``wire_gather_pages(self.k_pages, ...)`` export gather, or a
        direct subscript load of a page array."""
        best = None
        for n in ast.walk(m.node):
            hit = None
            if isinstance(n, ast.Call):
                tail = _call_tail(n)
                if tail in self._READ_CALL_TAILS:
                    hit = (n, f"{tail}(...)")
            elif (isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Attribute)
                    and n.value.attr in _PAGE_ATTRS
                    and isinstance(n.ctx, ast.Load)):
                hit = (n, f"{n.value.attr}[...]")
            if hit is None:
                continue
            if best is None or hit[0].lineno < best[0].lineno:
                best = hit
        return best

    @staticmethod
    def _flushes_before(m: FuncInfo, line: int) -> bool:
        for n in ast.walk(m.node):
            if (isinstance(n, ast.Call)
                    and _call_tail(n) == "_flush_commits"
                    and n.lineno < line):
                return True
        return False


# ---------------------------------------------------------------------------
# TPL213: page release only after the in-flight guard
# ---------------------------------------------------------------------------

class ReleaseBeforeGuard(InterprocChecker):
    """Scheduler-owned pages (``owned`` buffers, the ``_deferred_free``
    list) may only return to the pool after the in-flight-program guard:
    a dispatched program may still write the pages, so an unguarded
    release lets the allocator hand them to a new request mid-write."""

    rule = "TPL213"
    name = "release-before-guard"
    severity = "error"
    description = ("pool.release of scheduler-owned pages with no "
                   "in-flight-program guard earlier in the function")

    def finalize(self):
        p = self.project
        if p is None:
            return
        p.link()
        for f in p.functions:
            if _in_tests(f) or f.is_module:
                continue
            for site in f.calls:
                parts = site.target.split(".")
                if parts[-1] != "release" or len(parts) < 2:
                    continue
                if not any("pool" in part for part in parts[:-1]):
                    continue
                owned = set()
                for a in site.node.args:
                    owned |= _idents(a) & _OWNED_ARGS
                if not owned:
                    continue
                if self._guarded_before(f, site.node.lineno):
                    continue
                self.report(
                    site.node,
                    f"release of scheduler-owned pages "
                    f"({', '.join(sorted(owned))}) with no in-flight "
                    "guard (_inflight / defer test) earlier in "
                    f"'{f.name}' — an in-flight program may still write "
                    "these pages; gate the release on the in-flight "
                    "handle being harvested",
                    path=f.path)

    @staticmethod
    def _guarded_before(f: FuncInfo, line: int) -> bool:
        for n in ast.walk(f.node):
            if getattr(n, "lineno", line) >= line:
                continue
            if isinstance(n, ast.Name) and n.id in _GUARD_IDS:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _GUARD_IDS:
                return True
        return False


TYPESTATE_CHECKERS = [AdoptProtocol, StagedFlushBarrier, ReleaseBeforeGuard]
