"""tpu-shardcheck: whole-program static sharding & collective verifier.

The dynamic layers (contracts.py, the multichip smoke) observe sharding
properties by *running* programs; the involuntary-remat guard in
``__graft_entry__.py`` was, until this module, an FD-level grep of the
C++ SPMD partitioner's glog output.  shardcheck proves the same
properties from the **jaxpr**, before any device executes anything:

1. every registered entry program (the dp×pp×mp train step, the unified
   RPA serving step, the disagg wire stage/commit kernels, the
   quantized all-reduce) is traced to a closed jaxpr,
2. an abstract interpreter propagates PartitionSpecs through every
   equation — recursing into scan/remat2/pjit/shard_map/custom-vjp
   bodies exactly as ``compiler/fusion_pass.py`` recurses for fusion
   discovery,
3. four rule families fire on the propagated environment:

   TPL201 involuntary-reshard  a gather/dot whose *parameter* operand is
          sharded on a lookup/contraction dim and whose output is not
          pinned by a ``with_sharding_constraint`` — the exact shape of
          the MULTICHIP_r05 involuntary full rematerialization, reported
          at the offending eqn with the missing ``*_constraint`` named.
   TPL202 collective-partial-manual  a collective inside a shard_map
          region whose mesh has size>1 axes *outside* the manual set —
          the ``dist_allreduce_quant`` pp>1/mp>1 refusal (and the
          pipeline's partial-manual 1F1B region), flagged statically
          instead of at lowering time.
   TPL203 collective-order  two programs registered as interleavable
          (fleet wire commit vs. in-flight step) must issue their common
          collectives in a consistent global order or a cross-program
          deadlock is reachable.
   TPL204 vmem-overflow  a static roofline estimate per fusion-catalog
          Site (``fusion_pass.site_vmem_bytes``) against the ~16 MiB
          per-core VMEM budget — the seed of the cost-model scheduler.

Baseline/suppression semantics mirror ``contracts.py``: known findings
carry a rationale in :data:`EXPLAINED` (the JSON analog of a lint
suppression, keyed ``(entry, rule)``), everything else is drift-checked
against ``artifacts/shardcheck.json``.  Wired as ``python -m tools.lint
--shardcheck`` with the same exit codes (0 clean / 1 findings or drift /
2 usage / 3 missing baseline) and rendered by the existing reporters.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

from .core import Finding

__all__ = [
    "EntryProgram",
    "ShardInterp",
    "EXPLAINED",
    "VMEM_BUDGET_BYTES",
    "build_entries",
    "build_report",
    "check_entry",
    "diff_baselines",
    "load_baseline",
    "spec_environment",
    "unexplained_findings",
    "write_baseline",
]

# TPU v5e-class cores hold ~16 MiB of VMEM (pallas guide); a fused site
# whose double-buffered working set exceeds this cannot stay resident.
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

# Known findings with rationales — the contracts.EXPLAINED analog.  A
# finding keyed here is reported in the baseline but does not fail the
# run; an EXPLAINED key with no matching finding is itself drift (stale
# rationales must be pruned like stale suppressions).
EXPLAINED = {
    ("train_dp2_pp2_mp2", "TPL202"):
        "the 1F1B pipeline region is partial-manual by design (pp manual,"
        " dp/mp auto); it lowers only on runtimes with native"
        " partial-manual shard_map — tests skip it via"
        " requires_native_partial_manual, shardcheck documents it here",
    ("quant_allreduce_dp2pp2", "TPL202"):
        "the known dist_allreduce_quant pp>1 refusal: train_step raises"
        " ValueError for this mesh before tracing; the entry exists so"
        " the refusal is proven static, not discovered at lowering",
}

# Collective primitives as they appear as jaxpr eqn names.
COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter",
}

# Primitives that pass sharding (and parameter-ness) through unchanged.
_TRANSPARENT = {
    "convert_element_type", "copy", "stop_gradient", "device_put",
    "optimization_barrier", "reduce_precision",
}


# ---------------------------------------------------------------------------
# spec domain
# ---------------------------------------------------------------------------
# A spec is a tuple over array dims; each entry is a frozenset of mesh
# axis names the dim is sharded over (empty = replicated on that dim).

def _nd(aval) -> int:
    return len(getattr(aval, "shape", ()) or ())


def _empty_spec(ndim: int) -> tuple:
    return (frozenset(),) * ndim


def _spec_from_partition(pspec, ndim: int) -> tuple:
    """PartitionSpec -> internal spec tuple (padded to ndim)."""
    out = []
    entries = tuple(pspec) if pspec is not None else ()
    for d in range(ndim):
        e = entries[d] if d < len(entries) else None
        if e is None:
            out.append(frozenset())
        elif isinstance(e, (tuple, list)):
            out.append(frozenset(x for x in e if x is not None))
        else:
            out.append(frozenset([e]))
    return tuple(out)


def _spec_str(spec) -> str:
    if spec is None:
        return "?"
    return "(" + ",".join(
        ("+".join(sorted(d)) if d else "-") for d in spec) + ")"


def _join_dim(a: frozenset, b: frozenset) -> frozenset:
    """Join two per-dim assignments: agreement wins, else first
    non-empty (a conflict means the partitioner will reshard — the
    propagation tracks the dominant layout)."""
    if a == b:
        return a
    return a if a else b


def _join_spec(a, b):
    if a is None:
        return b
    if b is None:
        return a
    if len(a) != len(b):
        return a
    return tuple(_join_dim(x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# entry programs
# ---------------------------------------------------------------------------

@dataclass
class EntryProgram:
    """One registered program: a closed jaxpr plus the sharding facts
    the tracer cannot recover from the jaxpr alone."""

    name: str
    closed: object                        # jax ClosedJaxpr
    mesh_axes: dict                       # axis name -> size
    in_specs: list                        # spec tuple per invar
    source: str                           # repo path the program comes from
    invar_names: list = field(default_factory=list)
    interleave: str | None = None         # TPL203 group
    param_invars: set = field(default_factory=set)  # invar indices that
    #                                      are weights (TPL201 operands)


def _jax():
    """Import jax late, forcing an 8-device virtual CPU platform when
    this process has not initialized a backend yet (the CLI path; under
    pytest the conftest already did this)."""
    if "jax" not in os.sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    return jax


def _need_devices(n: int):
    jax = _jax()
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"shardcheck needs {n} devices to build meshes but the "
            f"already-initialized backend has {len(devs)}; run in a "
            "fresh process (python -m tools.lint --shardcheck) or set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    return devs


def _tiny_gpt_cfg():
    from paddle_tpu.models.gpt import GPTConfig

    return GPTConfig(vocab_size=128, hidden=16, n_layers=2, n_heads=2,
                     seq_len=16)


def _flatten_names(tree) -> list:
    jax = _jax()
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [jax.tree_util.keystr(path) for path, _ in leaves]


def build_train_entry(name: str = "train_dp2_pp2_mp2",
                      mesh_shape=(("dp", 2), ("pp", 2), ("mp", 2)),
                      emb_pin: bool = True,
                      batch: int = 8) -> EntryProgram:
    """Trace the sharded train step (parallel/train_step.py) to a jaxpr
    under ``abstract=True`` — no weights materialize.  ``emb_pin=False``
    rebuilds the PR 9 *pre-fix* program (embedding gather with the
    ``emb_constraint`` hook disabled) for the TPL201 regression."""
    import numpy as np

    jax = _jax()
    import paddle_tpu  # noqa: F401  -- installs the jax_compat shims
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel.train_step import make_sharded_train_step

    axes = [a for a, _ in mesh_shape]
    sizes = [s for _, s in mesh_shape]
    n_dev = int(np.prod(sizes))
    devs = _need_devices(n_dev)[:n_dev]
    mesh = Mesh(np.asarray(devs).reshape(sizes), axes)
    cfg = _tiny_gpt_cfg()
    step_fn, params, opt_state = make_sharded_train_step(
        cfg, mesh, abstract=True, _emb_pin=emb_pin)
    dp = dict(mesh_shape).get("dp", 1)
    tok = jax.ShapeDtypeStruct(
        (batch, cfg.seq_len), np.int32,
        sharding=NamedSharding(mesh, P("dp" if dp > 1 else None)))
    with jax.sharding.set_mesh(mesh):
        closed = jax.make_jaxpr(step_fn.jitted)(params, opt_state, tok, tok)
    leaves = (jax.tree_util.tree_leaves(params)
              + jax.tree_util.tree_leaves(opt_state) + [tok, tok])
    names = (["params" + n for n in _flatten_names(params)]
             + ["opt" + n for n in _flatten_names(opt_state)]
             + ["tokens", "labels"])
    in_specs = []
    for leaf in leaves:
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        in_specs.append(_spec_from_partition(spec, _nd(leaf)))
    n_params = len(jax.tree_util.tree_leaves(params))
    return EntryProgram(
        name=name, closed=closed, mesh_axes=dict(mesh_shape),
        in_specs=in_specs, invar_names=names,
        source="paddle_tpu/parallel/train_step.py",
        param_invars=set(range(n_params)))


def _tiny_engine():
    import jax.numpy as jnp

    from paddle_tpu.inference.serving import LlamaConfig, ServingEngine

    cfg = LlamaConfig(vocab_size=128, hidden=32, n_layers=2, n_heads=2,
                      n_kv_heads=2, ffn_hidden=64, max_seq_len=64,
                      dtype=jnp.float32, param_dtype=jnp.float32)
    return ServingEngine(cfg, max_batch=2, page_size=8, max_seq=64,
                         n_pages=1 + 8)


def build_serving_entries() -> list:
    """The unified RPA serving step plus the disagg wire stage/commit
    kernels, traced from one tiny single-device engine.  All three share
    the TPL203 interleave group: the wire runs between (stage) and
    before (commit) in-flight unified steps."""
    jax = _jax()
    import numpy as np

    from paddle_tpu.inference.serving import (wire_gather_pages,
                                              wire_scatter_pages)

    eng = _tiny_engine()
    unified = eng.trace_unified()
    out = [EntryProgram(
        name="serving_unified", closed=unified, mesh_axes={},
        in_specs=[_empty_spec(_nd(v.aval)) for v in unified.jaxpr.invars],
        source="paddle_tpu/inference/serving.py",
        interleave="serving-wire",
        param_invars=set(range(len(jax.tree_util.tree_leaves(eng.params)))))]
    kp = eng.k_pages
    n_ship = 2
    pg = jax.ShapeDtypeStruct((n_ship,), np.int32)
    staged = jax.ShapeDtypeStruct(
        (kp.shape[0], n_ship) + kp.shape[2:], kp.dtype)
    gather = jax.make_jaxpr(wire_gather_pages)(
        jax.ShapeDtypeStruct(kp.shape, kp.dtype), pg)
    scatter = jax.make_jaxpr(wire_scatter_pages)(
        jax.ShapeDtypeStruct(kp.shape, kp.dtype), pg, staged)
    for nm, closed in (("wire_stage", gather), ("wire_commit", scatter)):
        out.append(EntryProgram(
            name=nm, closed=closed, mesh_axes={},
            in_specs=[_empty_spec(_nd(v.aval))
                      for v in closed.jaxpr.invars],
            source="paddle_tpu/inference/serving.py",
            interleave="serving-wire"))
    return out


def build_quant_entry(name: str = "quant_allreduce_dp2pp2",
                      mesh_shape=(("dp", 2), ("pp", 2))) -> EntryProgram:
    """The quantized all-reduce (distributed/autograd_collectives.py)
    inside a dp-manual shard_map over a mesh with a second size>1 axis —
    exactly the partial-manual combination ``make_sharded_train_step``
    refuses with a ValueError.  Traced directly (the guard never runs),
    so TPL202 proves the refusal without executing any lowering."""
    import numpy as np

    jax = _jax()
    import paddle_tpu  # noqa: F401
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed.autograd_collectives import (
        dist_allreduce_quant)

    axes = [a for a, _ in mesh_shape]
    sizes = [s for _, s in mesh_shape]
    n_dev = int(np.prod(sizes))
    devs = _need_devices(n_dev)[:n_dev]
    mesh = Mesh(np.asarray(devs).reshape(sizes), axes)
    dp = dict(mesh_shape)["dp"]

    def body(g):
        return dist_allreduce_quant(g, "dp", mean=True, axis_size=dp)

    manual = {"dp"} | {a for a, s in mesh_shape if s == 1}
    run = jax.shard_map(body, in_specs=P("dp"), out_specs=P("dp"),
                        axis_names=manual, check_vma=False)
    g = jax.ShapeDtypeStruct((64, 16), np.float32)
    with jax.sharding.set_mesh(mesh):
        closed = jax.make_jaxpr(run)(g)
    return EntryProgram(
        name=name, closed=closed, mesh_axes=dict(mesh_shape),
        in_specs=[_spec_from_partition(P("dp"), 2)],
        source="paddle_tpu/distributed/autograd_collectives.py")


def build_entries(names=None) -> list:
    """All registered entry programs (optionally filtered by name)."""
    entries = [build_train_entry()]
    entries += build_serving_entries()
    entries.append(build_quant_entry())
    if names is not None:
        entries = [e for e in entries if e.name in set(names)]
    return entries


# ---------------------------------------------------------------------------
# the abstract interpreter
# ---------------------------------------------------------------------------

def _eqn_location(eqn):
    """(repo-relative path, line) of the user frame that created the
    eqn, best effort."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None, 0
        fname = frame.file_name
        line = getattr(frame, "start_line", None) or getattr(
            frame, "line_num", 0)
        for anchor in ("paddle_tpu/", "tools/", "tests/"):
            i = fname.find(anchor)
            if i >= 0:
                return fname[i:], int(line)
        return fname, int(line)
    except Exception:
        return None, 0


def _inner_closed(eqn):
    """[(closed-or-open jaxpr, consts)] bodies of a higher-order eqn —
    the fusion_pass._sub_jaxpr recursion generalized to every body the
    spec propagation must enter."""
    p = eqn.params
    name = eqn.primitive.name
    out = []
    if name == "scan" or name == "pjit":
        c = p["jaxpr"]
        out.append((c.jaxpr, c.consts))
    elif name == "remat2" or name == "custom_vjp_call_jaxpr":
        j = p.get("jaxpr") or p.get("fun_jaxpr")
        if hasattr(j, "jaxpr"):
            out.append((j.jaxpr, j.consts))
        else:
            out.append((j, []))
    elif name in ("custom_jvp_call", "custom_vjp_call"):
        c = p.get("call_jaxpr") or p.get("fun_jaxpr")
        if c is not None:
            if hasattr(c, "jaxpr"):
                out.append((c.jaxpr, c.consts))
            else:
                out.append((c, []))
    elif name == "while":
        c = p["body_jaxpr"]
        out.append((c.jaxpr, c.consts))
    elif name == "cond":
        for c in p["branches"]:
            out.append((c.jaxpr, c.consts))
    elif name == "shard_map":
        j = p["jaxpr"]
        if hasattr(j, "jaxpr"):
            out.append((j.jaxpr, j.consts))
        else:
            out.append((j, []))
    return out


def _axes_of(eqn) -> tuple:
    """Mesh axis names a collective eqn communicates over."""
    p = eqn.params
    raw = p.get("axes", p.get("axis_name", ()))
    if raw is None:
        raw = ()
    if isinstance(raw, (str,)):
        raw = (raw,)
    out = []
    for a in raw:
        if isinstance(a, str):
            out.append(a)
    return tuple(sorted(out))


@dataclass
class _Region:
    """Ambient shard_map context while interpreting a body."""

    mesh_axes: dict                 # full mesh at this point
    manual: frozenset = frozenset()


class ShardInterp:
    """Propagates specs through one entry program and collects rule
    events.  One instance per entry; findings accumulate on
    ``self.findings`` and the full var environment (for the golden
    spec-environment test) on ``self.all_specs``."""

    def __init__(self, entry: EntryProgram):
        self.entry = entry
        self.findings: list[Finding] = []
        self.collective_events: list[tuple] = []   # (prim, axes, path, line)
        self.all_specs: dict[str, int] = {}        # spec str -> count
        self.out_specs: list = []

    # -- env helpers --------------------------------------------------------

    @staticmethod
    def _read(env, atom):
        import jax.core as jcore  # noqa: F401  (Literal check via name)

        if type(atom).__name__ == "Literal":
            return _empty_spec(_nd(atom.aval)), False
        return env.get(atom, (_empty_spec(_nd(atom.aval)), False))

    def _record(self, spec):
        self.all_specs[_spec_str(spec)] = \
            self.all_specs.get(_spec_str(spec), 0) + 1

    def _finding(self, rule, name, eqn, message, severity="error"):
        path, line = _eqn_location(eqn)
        self.findings.append(Finding(
            rule=rule, name=name, severity=severity,
            path=path or self.entry.source, line=line or 1, col=0,
            message=f"[entry {self.entry.name}] {message}"))

    # -- driver -------------------------------------------------------------

    def run(self):
        closed = self.entry.closed
        jaxpr = closed.jaxpr
        env = {}
        for cv in jaxpr.constvars:
            env[cv] = (_empty_spec(_nd(cv.aval)), False)
        params = self.entry.param_invars
        for i, v in enumerate(jaxpr.invars):
            spec = (self.entry.in_specs[i]
                    if i < len(self.entry.in_specs)
                    else _empty_spec(_nd(v.aval)))
            env[v] = (spec, i in params)
        region = _Region(mesh_axes=dict(self.entry.mesh_axes))
        self._interp(jaxpr, env, region)
        self.out_specs = [self._read(env, v)[0] for v in jaxpr.outvars]
        return self

    # -- interpretation -----------------------------------------------------

    def _interp(self, jaxpr, env, region):
        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            ins = [self._read(env, a) for a in eqn.invars]
            if name == "pjit":
                outs = self._do_pjit(eqn, ins, region)
            elif name == "scan":
                outs = self._do_scan(eqn, ins, region)
            elif name == "shard_map":
                outs = self._do_shard_map(eqn, ins, region)
            elif name in ("remat2", "custom_jvp_call", "custom_vjp_call",
                          "custom_vjp_call_jaxpr", "while", "cond"):
                outs = self._do_opaque_body(eqn, ins, region)
            else:
                if name in COLLECTIVE_PRIMS:
                    self._on_collective(eqn, region)
                if name == "gather":
                    self._check_gather(jaxpr, i, eqn, ins)
                if name == "dot_general":
                    self._check_dot(jaxpr, i, eqn, ins)
                outs = _propagate(eqn, ins)
            for v, o in zip(eqn.outvars, outs):
                if type(v).__name__ == "DropVar":
                    continue
                env[v] = o
                self._record(o[0])

    # -- higher-order handlers ----------------------------------------------

    def _run_body(self, jaxpr, consts, in_states, region):
        env = {}
        for cv in jaxpr.constvars:
            env[cv] = (_empty_spec(_nd(cv.aval)), False)
        for v, st in zip(jaxpr.invars, in_states):
            env[v] = st
        self._interp(jaxpr, env, region)
        return [self._read(env, v) for v in jaxpr.outvars], env

    def _do_pjit(self, eqn, ins, region):
        inner, consts = eqn.params["jaxpr"].jaxpr, eqn.params["jaxpr"].consts
        states = list(ins)
        for j, sh in enumerate(eqn.params.get("in_shardings", ()) or ()):
            spec = getattr(sh, "spec", None)
            if spec is not None and j < len(states):
                states[j] = (_spec_from_partition(
                    spec, _nd(inner.invars[j].aval)), states[j][1])
        n_consts = len(inner.constvars)
        del n_consts
        outs, _ = self._run_body(inner, consts, states, region)
        for j, sh in enumerate(eqn.params.get("out_shardings", ()) or ()):
            spec = getattr(sh, "spec", None)
            if spec is not None and j < len(outs):
                outs[j] = (_spec_from_partition(
                    spec, _nd(eqn.outvars[j].aval)), outs[j][1])
        return outs

    def _do_scan(self, eqn, ins, region):
        p = eqn.params
        inner = p["jaxpr"].jaxpr
        consts = p["jaxpr"].consts
        nc, ncarry = p["num_consts"], p["num_carry"]
        const_in = ins[:nc]
        carry_in = ins[nc:nc + ncarry]
        xs_in = ins[nc + ncarry:]
        # xs enter the body with the leading scan dim stripped
        xs_body = [((s[1:] if s else s), pf) for s, pf in xs_in]
        carry = list(carry_in)
        outs = None
        for _ in range(2):                     # carry fixpoint (2 sweeps)
            outs, _ = self._run_body(
                inner, consts, const_in + carry + xs_body, region)
            new_carry = outs[:ncarry]
            carry = [(_join_spec(a[0], b[0]), a[1] or b[1])
                     for a, b in zip(carry, new_carry)]
        ys = [((frozenset(),) + s if s is not None else s, pf)
              for s, pf in outs[ncarry:]]
        return carry + ys

    def _do_shard_map(self, eqn, ins, region):
        p = eqn.params
        mesh = p.get("mesh")
        mesh_axes = dict(region.mesh_axes)
        if mesh is not None and getattr(mesh, "shape", None):
            try:
                mesh_axes = dict(mesh.shape)
            except Exception:
                pass
        auto = frozenset(p.get("auto", frozenset()) or frozenset())
        manual = frozenset(a for a in mesh_axes if a not in auto)
        inner_region = _Region(mesh_axes=mesh_axes,
                               manual=region.manual | manual)
        bodies = _inner_closed(eqn)
        if not bodies:
            return _propagate(eqn, ins)
        inner, consts = bodies[0]
        # inside the manual region the named axes are local: strip them
        states = []
        for (s, pf), names in zip(ins, p.get("in_names", ()) or ()):
            if s is not None and isinstance(names, dict):
                manual_axes = {a for axs in names.values() for a in axs}
                s = tuple(d - manual_axes for d in s)
            states.append((s, pf))
        while len(states) < len(inner.invars):
            states.append((_empty_spec(0), False))
        outs, _ = self._run_body(inner, consts,
                                 states[:len(inner.invars)], inner_region)
        res = []
        for j, v in enumerate(eqn.outvars):
            names = None
            out_names = p.get("out_names", ()) or ()
            if j < len(out_names) and isinstance(out_names[j], dict):
                names = out_names[j]
            s = outs[j][0] if j < len(outs) else _empty_spec(_nd(v.aval))
            if s is not None and names:
                s = list(s if len(s) == _nd(v.aval)
                         else _empty_spec(_nd(v.aval)))
                for d, axs in names.items():
                    if d < len(s):
                        s[d] = s[d] | frozenset(axs)
                s = tuple(s)
            res.append((s, False))
        return res

    def _do_opaque_body(self, eqn, ins, region):
        bodies = _inner_closed(eqn)
        if not bodies:
            return _propagate(eqn, ins)
        results = None
        for inner, consts in bodies:
            states = list(ins)
            n = len(inner.invars)
            if eqn.primitive.name == "cond":
                states = states[1:]            # predicate operand
            if len(states) > n:
                states = states[-n:]
            while len(states) < n:
                states.insert(0, (_empty_spec(0), False))
            outs, _ = self._run_body(inner, consts, states, region)
            if results is None:
                results = outs
            else:
                results = [(_join_spec(a[0], b[0]), a[1] or b[1])
                           for a, b in zip(results, outs)]
        n_out = len(eqn.outvars)
        results = (results or [])[:n_out]
        while len(results) < n_out:
            results.append((_empty_spec(_nd(eqn.outvars[len(results)].aval)),
                            False))
        return [(s if s is not None and len(s) == _nd(v.aval)
                 else _empty_spec(_nd(v.aval)), pf)
                for (s, pf), v in zip(results, eqn.outvars)]

    # -- rules --------------------------------------------------------------

    def _on_collective(self, eqn, region):
        axes = _axes_of(eqn)
        path, line = _eqn_location(eqn)
        self.collective_events.append(
            (eqn.primitive.name, axes, path, line))
        partial = sorted(
            a for a, size in region.mesh_axes.items()
            if size > 1 and a not in region.manual)
        if region.manual and partial:
            self._finding(
                "TPL202", "collective-partial-manual", eqn,
                f"collective '{eqn.primitive.name}' over axes "
                f"{list(axes)} sits in a partial-manual shard_map region "
                f"(manual={sorted(region.manual & set(region.mesh_axes))}, "
                f"auto size>1 axes={partial}); this lowering is refused "
                "at runtime — restrict the mesh to the manual axes or "
                "make every size>1 axis manual")

    @staticmethod
    def _is_pinned(jaxpr, idx, eqn):
        """The eqn's output is pinned when a sharding_constraint consumes
        it within two transparent hops — the ``*_constraint`` idiom."""
        uses: dict = {}
        for j, e in enumerate(jaxpr.eqns):
            for a in e.invars:
                if type(a).__name__ != "Literal":
                    uses.setdefault(a, []).append(j)
        frontier = [v for v in eqn.outvars]
        for _ in range(3):
            nxt = []
            for v in frontier:
                for j in uses.get(v, []):
                    e = jaxpr.eqns[j]
                    if e.primitive.name == "sharding_constraint":
                        return True
                    if e.primitive.name in _TRANSPARENT:
                        nxt.extend(e.outvars)
            frontier = nxt
            if not frontier:
                break
        return False

    def _check_gather(self, jaxpr, idx, eqn, ins):
        (op_spec, op_param) = ins[0]
        if not op_param or op_spec is None:
            return
        dims = eqn.params.get("dimension_numbers")
        slice_sizes = eqn.params.get("slice_sizes", ())
        op_shape = getattr(eqn.invars[0].aval, "shape", ())
        lookup = set(getattr(dims, "start_index_map", ()) or ())
        hot = sorted(
            d for d in lookup
            if d < len(op_spec) and op_spec[d]
            and d < len(slice_sizes) and d < len(op_shape)
            and slice_sizes[d] < op_shape[d])
        if not hot:
            return
        if self._is_pinned(jaxpr, idx, eqn):
            return
        axes = sorted(a for d in hot for a in op_spec[d])
        self._finding(
            "TPL201", "involuntary-reshard", eqn,
            f"gather over a parameter sharded {_spec_str(op_spec)} on its "
            f"lookup dim(s) {hot} (axes {axes}) has no "
            "with_sharding_constraint pin on its output — GSPMD will "
            "invent an intermediate layout and reshard it, the "
            "involuntary full-rematerialization; pin the output via the "
            "*_constraint hook at the gather (see "
            "train_step.emb_constraint)")

    def _check_dot(self, jaxpr, idx, eqn, ins):
        (l_spec, l_param) = ins[0]
        (r_spec, r_param) = ins[1]
        if l_spec is None or r_spec is None:
            return
        dims = eqn.params.get("dimension_numbers")
        try:
            (lc, rc), _ = dims
        except Exception:
            return
        for dl, dr in zip(lc, rc):
            if dl >= len(l_spec) or dr >= len(r_spec):
                continue
            a, b = l_spec[dl], r_spec[dr]
            if a and b and a != b and (l_param or r_param):
                if self._is_pinned(jaxpr, idx, eqn):
                    continue
                self._finding(
                    "TPL201", "involuntary-reshard", eqn,
                    f"dot contracting dim {dl}x{dr} is sharded "
                    f"{sorted(a)} on the left but {sorted(b)} on the "
                    "right with a parameter operand and no constraint "
                    "pin — meeting the consumer forces a full-replica "
                    "materialization of the parameter; pin one side with "
                    "with_sharding_constraint")


# default propagation --------------------------------------------------------

def _propagate(eqn, ins):
    """Per-primitive spec transfer for first-order eqns."""
    name = eqn.primitive.name
    outs = eqn.outvars
    p = eqn.params

    def mk(spec, pf=False):
        return [(spec if spec is not None and len(spec) == _nd(v.aval)
                 else _empty_spec(_nd(v.aval)), pf) for v in outs]

    if not ins:
        return mk(None)
    (s0, pf0) = ins[0]
    if name in _TRANSPARENT:
        return mk(s0, pf0)
    if name == "sharding_constraint":
        sh = p.get("sharding")
        spec = getattr(sh, "spec", None)
        if spec is not None:
            return mk(_spec_from_partition(spec, _nd(outs[0].aval)))
        return mk(s0)
    if name == "transpose":
        perm = p.get("permutation", ())
        if s0 is not None and len(perm) == len(s0):
            return mk(tuple(s0[d] for d in perm))
        return mk(None)
    if name == "broadcast_in_dim":
        bdims = p.get("broadcast_dimensions", ())
        nd = _nd(outs[0].aval)
        spec = [frozenset()] * nd
        if s0 is not None:
            for src, dst in enumerate(bdims):
                if src < len(s0) and dst < nd:
                    spec[dst] = s0[src]
        return mk(tuple(spec))
    if name in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
                "reduce_and", "reduce_or", "argmax", "argmin"):
        axes = set(p.get("axes", ()))
        if s0 is not None:
            return mk(tuple(d for i, d in enumerate(s0) if i not in axes))
        return mk(None)
    if name == "squeeze":
        dims = set(p.get("dimensions", ()))
        if s0 is not None:
            return mk(tuple(d for i, d in enumerate(s0) if i not in dims))
        return mk(None)
    if name == "expand_dims":
        dims = set(p.get("dimensions", ()))
        if s0 is not None:
            spec, j = [], 0
            for i in range(_nd(outs[0].aval)):
                if i in dims:
                    spec.append(frozenset())
                elif j < len(s0):
                    spec.append(s0[j])
                    j += 1
                else:
                    spec.append(frozenset())
            return mk(tuple(spec))
        return mk(None)
    if name == "reshape":
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        out_shape = getattr(outs[0].aval, "shape", ())
        if s0 is not None and tuple(in_shape) == tuple(out_shape):
            return mk(s0)
        # size-1 insertion/removal: map surviving dims in order
        if s0 is not None:
            in_nz = [(i, d) for i, d in enumerate(in_shape) if d != 1]
            out_nz = [i for i, d in enumerate(out_shape) if d != 1]
            if (len(in_nz) == len(out_nz)
                    and [d for _, d in in_nz]
                    == [out_shape[i] for i in out_nz]):
                spec = [frozenset()] * len(out_shape)
                for (src, _), dst in zip(in_nz, out_nz):
                    spec[dst] = s0[src]
                return mk(tuple(spec))
        return mk(None)
    if name == "dot_general":
        (l, _), (r, _) = ins[0], ins[1]
        try:
            (lc, rc), (lb, rb) = p["dimension_numbers"]
        except Exception:
            return mk(None)
        if l is None or r is None:
            return mk(None)
        lf = [d for d in range(len(l)) if d not in set(lc) | set(lb)]
        rf = [d for d in range(len(r)) if d not in set(rc) | set(rb)]
        spec = tuple([l[d] for d in lb] + [l[d] for d in lf]
                     + [r[d] for d in rf])
        seen: set = set()
        clean = []
        for d in spec:
            keep = d - seen
            seen |= keep
            clean.append(keep)
        return mk(tuple(clean))
    if name == "gather":
        # output batch dims follow the indices; slice dims follow the
        # operand's offset dims (replicated lookup dims collapse away)
        s_idx = ins[1][0] if len(ins) > 1 else None
        dims = p.get("dimension_numbers")
        nd = _nd(outs[0].aval)
        offset = list(getattr(dims, "offset_dims", ()) or ())
        spec = [frozenset()] * nd
        if s_idx is not None:
            bi = 0
            for i in range(nd):
                if i not in offset and bi < max(len(s_idx) - 1, 0):
                    spec[i] = s_idx[bi]
                    bi += 1
        if s0 is not None:
            collapsed = set(getattr(dims, "collapsed_slice_dims", ())
                            or ())
            op_dims = [d for d in range(len(s0)) if d not in collapsed]
            for od, d in zip(offset, op_dims):
                if od < nd:
                    spec[od] = s0[d]
        return mk(tuple(spec))
    if name in ("scatter", "scatter-add", "scatter_add", "scatter_mul",
                "scatter_min", "scatter_max", "dynamic_update_slice"):
        return mk(s0, pf0)
    if name in ("dynamic_slice", "slice", "rev", "pad", "cumsum",
                "cumlogsumexp", "cummax", "cummin", "cumprod", "sort",
                "clamp", "select_and_scatter_add"):
        return mk(s0)
    if name == "concatenate":
        spec = None
        for s, _ in ins:
            spec = _join_spec(spec, s)
        if spec is not None:
            dim = p.get("dimension", 0)
            spec = tuple(frozenset() if i == dim else d
                         for i, d in enumerate(spec))
        return mk(spec)
    if name in COLLECTIVE_PRIMS:
        return mk(s0)
    if name == "iota":
        return mk(None)
    # default: positional join over same-rank inputs (elementwise family)
    nd = _nd(outs[0].aval)
    spec = None
    for s, _ in ins:
        if s is not None and len(s) == nd:
            spec = _join_spec(spec, s)
    return mk(spec)


# ---------------------------------------------------------------------------
# cross-program + fusion-site rules
# ---------------------------------------------------------------------------

def ordering_findings(events_by_entry: dict,
                      groups: dict) -> list:
    """TPL203: for every interleave group, every pair of programs must
    issue their *common* collectives (same primitive + axes) in the same
    relative order.  ``events_by_entry`` maps entry name -> ordered
    [(prim, axes, path, line)]; ``groups`` maps entry name -> group."""
    findings = []
    by_group: dict = {}
    for name, grp in groups.items():
        if grp:
            by_group.setdefault(grp, []).append(name)
    for grp, members in sorted(by_group.items()):
        for i in range(len(members)):
            for j in range(i + 1, len(members)):
                a, b = members[i], members[j]
                ea = [(p, ax) for p, ax, *_ in events_by_entry.get(a, [])]
                eb = [(p, ax) for p, ax, *_ in events_by_entry.get(b, [])]
                common = [k for k in dict.fromkeys(ea) if k in set(eb)]
                if len(common) < 2:
                    continue
                order_a = [k for k in dict.fromkeys(ea) if k in common]
                order_b = [k for k in dict.fromkeys(eb) if k in common]
                if order_a != order_b:
                    findings.append(Finding(
                        rule="TPL203", name="collective-order",
                        severity="error", path="tools/lint/shardcheck.py",
                        line=1, col=0,
                        message=(f"[entry {a}] interleavable programs "
                                 f"'{a}' and '{b}' (group {grp}) issue "
                                 f"common collectives in conflicting "
                                 f"order: {order_a} vs {order_b} — a "
                                 "cross-program deadlock is reachable; "
                                 "align the issue order")))
    return findings


def vmem_findings(entry_name: str, sites,
                  budget: int = VMEM_BUDGET_BYTES) -> list:
    """TPL204: static VMEM roofline per applied fusion Site."""
    from paddle_tpu.compiler.fusion_pass import site_vmem_bytes

    out = []
    for s in sites:
        if not getattr(s, "applied", False):
            continue
        est = site_vmem_bytes(s)
        if est > budget:
            out.append(Finding(
                rule="TPL204", name="vmem-overflow", severity="error",
                path="paddle_tpu/compiler/catalog.py", line=1, col=0,
                message=(f"[entry {entry_name}] fusion site "
                         f"'{s.template}' has an estimated double-"
                         f"buffered working set of {est} bytes "
                         f"(> {budget} VMEM budget); the fused kernel "
                         "cannot stay resident — shrink the block or "
                         "leave the site unfused")))
    return out


# ---------------------------------------------------------------------------
# report / baseline
# ---------------------------------------------------------------------------

def check_entry(entry: EntryProgram) -> tuple:
    """(interp, findings) for one entry: propagation rules plus the
    per-entry TPL204 fusion-site roofline."""
    interp = ShardInterp(entry).run()
    findings = list(interp.findings)
    try:
        from paddle_tpu.compiler.fusion_pass import plan_closed

        plan = plan_closed(entry.closed)
        findings += vmem_findings(entry.name, plan.walk())
    except Exception as e:  # pragma: no cover - fusion planning is
        # best-effort here; a planner bug must not kill the verifier
        findings.append(Finding(
            rule="TPL204", name="vmem-overflow", severity="warning",
            path=entry.source, line=1, col=0,
            message=f"[entry {entry.name}] fusion planning failed: "
                    f"{type(e).__name__}: {e}"))
    return interp, findings


def spec_environment(entry: EntryProgram) -> dict:
    """Deterministic summary of the full derived spec environment: the
    golden test pins this for the dp4×mp2 step."""
    interp = ShardInterp(entry).run()
    invars = {}
    for name, spec in zip(entry.invar_names, entry.in_specs):
        invars[name] = _spec_str(spec)
    return {
        "entry": entry.name,
        "mesh": dict(entry.mesh_axes),
        "invars": invars,
        "outvars": [_spec_str(s) for s in interp.out_specs],
        "spec_histogram": dict(sorted(interp.all_specs.items())),
    }


def _entry_digest(interp: ShardInterp) -> str:
    blob = json.dumps(
        {"specs": dict(sorted(interp.all_specs.items())),
         "outs": [_spec_str(s) for s in interp.out_specs]},
        sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def build_report(names=None) -> dict:
    """Run every registered entry; returns findings + the baseline
    payload."""
    entries = build_entries(names)
    findings: list[Finding] = []
    payload = {"version": 1, "entries": {}}
    events: dict = {}
    groups: dict = {}
    for entry in entries:
        interp, fs = check_entry(entry)
        findings += fs
        events[entry.name] = interp.collective_events
        groups[entry.name] = entry.interleave
        counts: dict = {}
        for f in fs:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        payload["entries"][entry.name] = {
            "source": entry.source,
            "mesh": dict(entry.mesh_axes),
            "n_eqns": _count_eqns(entry.closed.jaxpr),
            "collectives": [[p, list(ax)] for p, ax, *_ in
                            interp.collective_events],
            "findings": dict(sorted(counts.items())),
            "spec_digest": _entry_digest(interp),
        }
    order = ordering_findings(events, groups)
    findings += order
    for f in order:
        ent = f.message.split("]")[0].split()[-1]
        e = payload["entries"].get(ent)
        if e is not None:
            e["findings"]["TPL203"] = e["findings"].get("TPL203", 0) + 1
    payload["explained"] = sorted(
        [k, r] for (k, r) in EXPLAINED)
    return {"findings": findings, "baseline": payload}


def _count_eqns(jaxpr) -> int:
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        for inner, _ in _inner_closed(eqn):
            n += _count_eqns(inner)
    return n


def _finding_entry(f: Finding) -> str:
    msg = f.message
    if msg.startswith("[entry "):
        return msg[len("[entry "):].split("]")[0]
    return ""


def unexplained_findings(findings: list) -> list:
    return [f for f in findings
            if (_finding_entry(f), f.rule) not in EXPLAINED]


def stale_explanations(findings: list) -> list:
    """EXPLAINED keys with no matching finding — stale rationales are
    drift, exactly like a suppression on dead code."""
    seen = {(_finding_entry(f), f.rule) for f in findings}
    return sorted(f"stale explanation: entry '{k}' rule {r} no longer "
                  "fires — prune it from shardcheck.EXPLAINED"
                  for (k, r) in EXPLAINED if (k, r) not in seen)


def write_baseline(payload: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def diff_baselines(current: dict, base: dict) -> list:
    """Human-readable drift lines, contracts.diff_baselines-style."""
    out = []
    cur_e = current.get("entries", {})
    base_e = base.get("entries", {})
    for name in sorted(set(cur_e) | set(base_e)):
        a, b = cur_e.get(name), base_e.get(name)
        if a is None:
            out.append(f"entry '{name}': removed (in baseline only)")
            continue
        if b is None:
            out.append(f"entry '{name}': new (not in baseline)")
            continue
        for key in ("mesh", "n_eqns", "collectives", "findings",
                    "spec_digest", "source"):
            if a.get(key) != b.get(key):
                out.append(f"entry '{name}': {key} drifted: "
                           f"{b.get(key)!r} -> {a.get(key)!r}")
    if current.get("explained") != base.get("explained"):
        out.append("explained set drifted: "
                   f"{base.get('explained')!r} -> "
                   f"{current.get('explained')!r}")
    return out
