"""tpu-verify part B: abstract op-contract verification.

Every op registered through ``core.dispatch.op`` declares a contract
implicitly: its impl's signature is the schema, its jax lowering the
kernel, its vjp the grad rule (dispatch.py docstring).  Nothing in the
repo checked that those contracts actually *hold* under abstract
evaluation — an op whose output dtype drifts, whose vjp aborts, or
whose zero-bubble split rule produces misshapen grads only fails when
a model happens to hit it on real hardware.

This module runs ``jax.eval_shape`` over the whole registry with a
generated matrix of abstract inputs and records, per op:

- the canonical abstract case (input/output shapes + dtypes),
- a broadcasting case for multi-array ops,
- a weak-type case (python scalar in slot 0),
- the same case under ``jax_enable_x64`` (dtype-promotion drift: a
  well-behaved op keeps float32 results float32; impls that mix
  np.float64 constants silently upcast — the drift only x64 exposes),
- an abstract vjp probe for ``differentiable=True`` ops (shape-checked
  against the inputs),
- an abstract probe of the op's ``register_split_vjp`` rule, if any.

Ops that cannot be abstractly evaluated with any generated case are
recorded as ``opaque`` with the error class (``ConcretizationTypeError``
is itself signal: the op graph-breaks under capture).  The result is a
machine-readable baseline (``artifacts/op_contracts.json``); future PRs
diff against it, so dtype/rank changes can never land silently.

Checked violations (must be empty or explained in ``EXPLAINED``):

- ``x64-upcast``        float32-in/float32-out op emits float64 under x64
- ``vjp-abort``         differentiable op whose vjp dies abstractly
- ``grad-shape-mismatch``  vjp grads disagree with input shapes
- ``split-vjp-abort``   a register_split_vjp rule dies abstractly
- ``split-grad-shape-mismatch``  split-rule grads disagree with inputs

Import is lazy: ``tools.lint`` stays importable without jax.
"""

from __future__ import annotations

import importlib
import inspect
import json
import os

__all__ = [
    "REGISTRY_MODULES",
    "EXPLAINED",
    "load_registry",
    "build_contracts",
    "unexplained_violations",
    "diff_baselines",
    "write_baseline",
    "load_baseline",
]

# Every lazily-registering module, pinned so the registry is complete and
# deterministic (same list as tests/test_grad_coverage.py).
REGISTRY_MODULES = [
    "paddle_tpu",
    "paddle_tpu.distributed.autograd_collectives",
    "paddle_tpu.geometric",
    "paddle_tpu.incubate.nn.functional",
    "paddle_tpu.models.gpt",
    "paddle_tpu.ops.parity",
    "paddle_tpu.quantization",
    "paddle_tpu.signal",
    "paddle_tpu.text",
    "paddle_tpu.vision.ops",
]

# Known, justified contract violations: op name -> {kind: rationale}.
# The analog of a lint suppression comment — every entry documents WHY
# the op is allowed to violate the abstract contract.
EXPLAINED: dict = {
    "qr": {
        "vjp-abort":
            "jax implements QR differentiation only for full-rank "
            "tall/square inputs (m >= n); the canonical wide f32[2,3] "
            "abstract case aborts upstream with NotImplementedError. "
            "Square-case gradients are exercised concretely by the "
            "grad inventory (tests/test_grad_coverage.py, SPD(3)).",
    },
}

# Parameter-name heuristics for non-array required parameters.
_AXIS_NAMES = {"axis", "dim", "start_axis", "stop_axis"}
_INT_NAMES = {
    "k", "n", "num", "depth", "repeats", "shifts", "decimals", "diagonal",
    "offset", "groups", "num_classes", "num_heads", "blocks", "chunks",
    "sections", "num_or_sections", "upscale_factor", "downscale_factor",
    "kernel_size", "stride", "num_partitions", "world_size", "nranks",
    "block_size", "max_len", "maxlen", "num_embeddings", "window_length",
    "n_fft", "num_samples", "num_buckets", "bits",
}
_FLOAT_NAMES = {
    "alpha", "beta", "eps", "epsilon", "rate", "scale", "min", "max",
    "min_val", "max_val", "momentum", "negative_slope", "delta", "lambd",
    "threshold", "value", "p", "q", "rcond", "tol", "dropout_rate",
    "smooth", "label_smoothing", "temperature", "margin", "clip",
}
_SHAPE_NAMES = {"shape", "sizes", "size", "repeat_times", "out_shape",
                "output_size", "perm", "dims", "axes"}


def load_registry() -> dict:
    """Import every registering module; return the live OP_REGISTRY."""
    for mod in REGISTRY_MODULES:
        importlib.import_module(mod)
    from paddle_tpu.core.dispatch import OP_REGISTRY

    return OP_REGISTRY


def _dt(struct) -> str:
    """Compact 'f32[2,3]' leaf spec (with weak-type marker)."""
    import numpy as np

    short = {
        "float32": "f32", "float64": "f64", "float16": "f16",
        "bfloat16": "bf16", "int32": "i32", "int64": "i64",
        "int16": "i16", "int8": "i8", "uint8": "u8", "uint32": "u32",
        "bool": "b1", "complex64": "c64", "complex128": "c128",
    }.get(np.dtype(struct.dtype).name, str(np.dtype(struct.dtype).name))
    shape = ",".join(str(d) for d in struct.shape)
    weak = "*" if getattr(struct, "weak_type", False) else ""
    return f"{short}[{shape}]{weak}"


def _flat(out) -> list:
    import jax

    return [x for x in jax.tree_util.tree_leaves(out)
            if hasattr(x, "shape") and hasattr(x, "dtype")]


class _VarArg:
    """Pseudo-parameter standing in for one *args slot."""

    def __init__(self, i):
        self.name = f"args{i}"


def _required_params(impl) -> list | None:
    try:
        sig = inspect.signature(impl)
    except (TypeError, ValueError):
        return None
    out = []
    for p in sig.parameters.values():
        if p.kind is p.VAR_POSITIONAL and not out:
            # pure-varargs ops (block_diag(*inputs)): probe two arrays —
            # zero args would exercise the degenerate empty case only
            out.extend([_VarArg(0), _VarArg(1)])
        elif p.default is inspect.Parameter.empty and p.kind in (
                p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            out.append(p)
    return out


def _scalar_guess(pname: str):
    if pname in _AXIS_NAMES:
        return 0
    if pname in _INT_NAMES or pname.startswith(("num_", "n_")):
        return 2
    if pname in _FLOAT_NAMES:
        return 0.5
    if pname in _SHAPE_NAMES:
        return (2, 3)
    if pname == "dtype":
        return "float32"
    if pname == "equation":
        return "ij,jk->ik"          # einsum-style; pairs with (3,3) cases
    if pname in ("data_format", "format"):
        return "NCHW"
    if pname.startswith(("is_", "with_", "use_", "keep", "transpose_",
                         "reverse", "exclusive", "hard", "approximate",
                         "normalize", "training", "upscale")):
        return False
    return None  # treat as an abstract array


def _case_matrix(params) -> list:
    """Candidate abstract-argument tuples, tried in order.  Each entry is
    a list of values: jax.ShapeDtypeStruct for arrays, concrete python
    scalars for config parameters."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    names = [p.name for p in params]

    def build(shape, scalars=True, dtype=jnp.float32):
        vals = []
        for nm in names:
            guess = _scalar_guess(nm) if scalars else None
            vals.append(S(shape, dtype) if guess is None else guess)
        return vals

    cases = [
        build((2, 3)),
        build((3, 3)),
        build((4,)),
        build((2, 3, 4)),
        build((2, 3), scalars=False),          # every param abstract
        build((2, 2, 2)),
        build((), ),
        build((2, 3), dtype=jnp.int32),
    ]
    cases.append(build((4,), dtype=jnp.int32))   # 1-D integer data
    if len(names) >= 2:
        # embedding-style: integer ids in slot 0, float table in slot 1
        mixed = build((2, 3))
        mixed[0] = S((2, 3), jnp.int32)
        cases.append(mixed)
        # gather-style: integer index in the LAST array slot
        gather = build((3, 3))
        arr_slots = [i for i, v in enumerate(gather)
                     if isinstance(v, S)]
        if arr_slots:
            gather[arr_slots[-1]] = S((2, 2), jnp.int32)
            cases.append(gather)
    return cases


def _eval_case(impl, vals):
    import jax

    arr_idx = [i for i, v in enumerate(vals)
               if isinstance(v, jax.ShapeDtypeStruct)]

    def fn(*arrs):
        full = list(vals)
        for i, a in zip(arr_idx, arrs):
            full[i] = a
        return impl(*full)

    out = jax.eval_shape(fn, *[vals[i] for i in arr_idx])
    return fn, arr_idx, out


def _vjp_probe(fn, structs):
    """eval_shape over vjp + cotangent application; returns grad leaves."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def probe(*arrs):
        out, vjp_fn = jax.vjp(fn, *arrs)
        cts = jax.tree_util.tree_map(
            lambda o: (jnp.ones(o.shape, o.dtype)
                       if jnp.issubdtype(o.dtype, jnp.inexact)
                       else np.zeros(o.shape, jax.dtypes.float0)),
            out)
        return vjp_fn(cts)

    return _flat(jax.eval_shape(probe, *structs))


def _split_vjp_probe(rule, structs, out_structs):
    import jax

    w_slots = tuple(range(1, len(structs)))

    def probe(*arrs_and_cots):
        arrs = list(arrs_and_cots[:len(structs)])
        cots = list(arrs_and_cots[len(structs):])
        res = rule(arrs, w_slots, {"_positional_extras": []}, cots)
        if res is None:
            return ()
        in_grads, wgrad_fn = res
        return ([g for g in in_grads if g is not None],
                sorted(wgrad_fn().items()))

    return jax.eval_shape(probe, *structs, *out_structs)


def probe_op(name: str, opdef) -> dict:
    """Abstract contract record for one op."""
    import jax

    rec = {"differentiable": bool(opdef.differentiable),
           "amp": opdef.amp_policy}
    params = _required_params(opdef.impl)
    if params is None:
        rec.update(status="opaque", error="uninspectable-signature")
        return rec
    rec["arity"] = len(params)

    fn = arr_idx = out = vals = None
    last_err = None
    for case in _case_matrix(params):
        try:
            fn, arr_idx, out = _eval_case(opdef.impl, case)
            vals = case
            break
        except Exception as e:  # abstract eval may die arbitrarily deep
            last_err = type(e).__name__
            fn = None
    if fn is None:
        rec.update(status="opaque", error=last_err or "no-case")
        return rec

    structs = [vals[i] for i in arr_idx]
    rec["status"] = "ok"
    rec["case"] = {"in": [_dt(s) for s in structs],
                   "static": {params[i].name: repr(v)
                              for i, v in enumerate(vals)
                              if i not in arr_idx},
                   "out": [_dt(o) for o in _flat(out)]}
    violations = []

    # broadcasting probe: first two arrays as (2,1) x (1,3)
    if len(arr_idx) >= 2 and all(
            tuple(s.shape) == (2, 3) for s in structs[:2]):
        b = list(structs)
        b[0] = jax.ShapeDtypeStruct((2, 1), b[0].dtype)
        b[1] = jax.ShapeDtypeStruct((1, 3), b[1].dtype)
        try:
            rec["broadcast"] = [_dt(o) for o in _flat(
                jax.eval_shape(fn, *b))]
        except Exception as e:
            rec["broadcast"] = f"error:{type(e).__name__}"

    # weak-type probe: python scalar in slot 0
    if len(arr_idx) >= 2:
        try:
            rec["weak"] = [_dt(o) for o in _flat(
                jax.eval_shape(lambda *rest: fn(1.0, *rest),
                               *structs[1:]))]
        except Exception as e:
            rec["weak"] = f"error:{type(e).__name__}"

    # x64 drift probe: same abstract case with x64 enabled; a 32-bit
    # contract that silently widens is exactly the promotion drift that
    # only shows up when someone flips the flag (or moves to CPU golden
    # checks) — catch it here instead.
    prev = jax.config.jax_enable_x64
    try:
        jax.config.update("jax_enable_x64", True)
        x64_out = [_dt(o) for o in _flat(jax.eval_shape(fn, *structs))]
        rec["x64"] = x64_out
        base_out = rec["case"]["out"]
        if len(x64_out) == len(base_out):
            for b32, b64 in zip(base_out, x64_out):
                if b32.startswith("f32") and b64.startswith("f64"):
                    violations.append(
                        {"kind": "x64-upcast",
                         "detail": f"{b32} -> {b64}"})
                    break
    except Exception as e:
        rec["x64"] = f"error:{type(e).__name__}"
    finally:
        jax.config.update("jax_enable_x64", prev)

    # abstract vjp probe
    if not opdef.differentiable:
        rec["vjp"] = "skipped"
    else:
        import jax.numpy as jnp

        if not any(jnp.issubdtype(o.dtype, jnp.inexact)
                   for o in _flat(out)):
            rec["vjp"] = "nondiff-output"
        else:
            try:
                grads = _vjp_probe(fn, structs)
                rec["vjp"] = "ok"
                rec["grads"] = [_dt(g) for g in grads]
                if len(grads) == len(structs):
                    for g, s in zip(grads, structs):
                        if (g.dtype != jax.dtypes.float0
                                and tuple(g.shape) != tuple(s.shape)):
                            violations.append(
                                {"kind": "grad-shape-mismatch",
                                 "detail": f"grad {_dt(g)} vs input "
                                           f"{_dt(s)}"})
                            break
            except Exception as e:
                rec["vjp"] = f"error:{type(e).__name__}"
                violations.append(
                    {"kind": "vjp-abort",
                     "detail": type(e).__name__})

    rec["violations"] = violations
    return rec


def _probe_split_rules(registry, contracts) -> None:
    """Abstract-run every register_split_vjp rule with matmul-shaped
    inputs; grafts results into the owning op's record."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import SPLIT_VJP

    S = jax.ShapeDtypeStruct
    shapes = {
        2: [S((2, 3), jnp.float32), S((3, 4), jnp.float32)],
        3: [S((2, 3), jnp.float32), S((3, 4), jnp.float32),
            S((4,), jnp.float32)],
    }
    for name in sorted(SPLIT_VJP):
        rec = contracts["ops"].get(name)
        if rec is None:
            rec = contracts["ops"][name] = {"status": "split-only",
                                            "violations": []}
        rule = SPLIT_VJP[name]
        results = {}
        for arity, structs in sorted(shapes.items()):
            out_structs = [S((2, 4), jnp.float32)]
            try:
                res = _split_vjp_probe(rule, structs, out_structs)
                leaves = _flat(res)
                results[str(arity)] = [_dt(x) for x in leaves]
                # first leaf is dx — must match input 0
                if leaves and tuple(leaves[0].shape) != (2, 3):
                    rec.setdefault("violations", []).append(
                        {"kind": "split-grad-shape-mismatch",
                         "detail": f"dx {_dt(leaves[0])} vs input "
                                   "f32[2,3]"})
            except Exception as e:
                results[str(arity)] = f"error:{type(e).__name__}"
                rec.setdefault("violations", []).append(
                    {"kind": "split-vjp-abort",
                     "detail": f"arity {arity}: {type(e).__name__}"})
        rec["split_vjp"] = results


def build_contracts(registry=None) -> dict:
    """Full registry sweep -> deterministic, diffable contract dict."""
    import jax

    if registry is None:
        registry = load_registry()
    contracts = {
        "schema": 1,
        "jax": jax.__version__,
        "op_count": len(registry),
        "ops": {},
    }
    for name in sorted(registry):
        contracts["ops"][name] = probe_op(name, registry[name])
    _probe_split_rules(registry, contracts)
    counts = {"ok": 0, "opaque": 0, "violations": 0}
    for name, rec in contracts["ops"].items():
        counts[rec.get("status", "ok")] = counts.get(
            rec.get("status", "ok"), 0) + (1 if "status" in rec else 0)
        counts["violations"] += len(rec.get("violations", []))
    contracts["summary"] = {
        **counts,
        "unexplained": len(unexplained_violations(contracts)),
    }
    return contracts


def unexplained_violations(contracts: dict) -> list:
    """[(op, kind, detail)] for violations with no EXPLAINED rationale."""
    out = []
    for name, rec in sorted(contracts["ops"].items()):
        for v in rec.get("violations", []):
            if v["kind"] not in EXPLAINED.get(name, {}):
                out.append((name, v["kind"], v["detail"]))
    return out


def diff_baselines(current: dict, baseline: dict) -> list:
    """Human-readable drift lines between two contract dicts."""
    lines = []
    cur, base = current.get("ops", {}), baseline.get("ops", {})
    for name in sorted(set(base) - set(cur)):
        lines.append(f"removed op: {name}")
    for name in sorted(set(cur) - set(base)):
        lines.append(f"new op: {name} (regenerate the baseline)")
    for name in sorted(set(cur) & set(base)):
        if cur[name] != base[name]:
            fields = sorted(
                k for k in set(cur[name]) | set(base[name])
                if cur[name].get(k) != base[name].get(k))
            lines.append(f"contract drift: {name} ({', '.join(fields)})")
    if current.get("jax") != baseline.get("jax"):
        lines.append(f"jax version: baseline {baseline.get('jax')} "
                     f"vs current {current.get('jax')}")
    return lines


def write_baseline(contracts: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(contracts, f, indent=1, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)
