"""Entry point: ``python -m tools.lint paddle_tpu tests [--format=json]``."""

import sys

from .cli import main

sys.exit(main())
