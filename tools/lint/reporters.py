"""Finding reporters: human text and machine JSON."""

from __future__ import annotations

import json

from .core import Finding

__all__ = ["render_text", "render_json"]


def render_text(findings: list[Finding]) -> str:
    lines = [
        f"{f.path}:{f.line}:{f.col}: {f.rule}[{f.name}] {f.severity}: "
        f"{f.message}"
        for f in sorted(findings, key=Finding.sort_key)
    ]
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    lines.append(
        "tpu-lint: clean" if not findings
        else f"tpu-lint: {n_err} error(s), {n_warn} warning(s)"
    )
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    return json.dumps(
        {
            "findings": [f.as_dict()
                         for f in sorted(findings, key=Finding.sort_key)],
            "summary": {
                "errors": sum(1 for f in findings if f.severity == "error"),
                "warnings": sum(1 for f in findings
                                if f.severity == "warning"),
            },
        },
        indent=2,
    )
