"""Finding reporters: human text, machine JSON, and SARIF for CI
per-rule annotation."""

from __future__ import annotations

import json

from .core import Finding

__all__ = ["render_text", "render_json", "render_sarif"]


def render_text(findings: list[Finding]) -> str:
    lines = [
        f"{f.path}:{f.line}:{f.col}: {f.rule}[{f.name}] {f.severity}: "
        f"{f.message}"
        for f in sorted(findings, key=Finding.sort_key)
    ]
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    lines.append(
        "tpu-lint: clean" if not findings
        else f"tpu-lint: {n_err} error(s), {n_warn} warning(s)"
    )
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    return json.dumps(
        {
            "findings": [f.as_dict()
                         for f in sorted(findings, key=Finding.sort_key)],
            "summary": {
                "errors": sum(1 for f in findings if f.severity == "error"),
                "warnings": sum(1 for f in findings
                                if f.severity == "warning"),
            },
        },
        indent=2,
    )


_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def render_sarif(findings: list[Finding], tool_name: str = "tpu-lint",
                 tool_version: str = "1.0") -> str:
    """SARIF 2.1.0 — the format CI annotation surfaces consume. One run,
    one rule entry per distinct rule id, one result per finding; SARIF
    columns are 1-based where Finding.col is 0-based."""
    ordered = sorted(findings, key=Finding.sort_key)
    rules: dict[str, dict] = {}
    results = []
    for f in ordered:
        rules.setdefault(f.rule, {
            "id": f.rule,
            "name": f.name,
            "shortDescription": {"text": f.name},
        })
        results.append({
            "ruleId": f.rule,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        })
    doc = {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "version": tool_version,
                "rules": [rules[k] for k in sorted(rules)],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)
