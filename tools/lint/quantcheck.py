"""tpu-quantcheck: static precision & scale-provenance verifier.

shardcheck proves *layout* properties of the registered entry programs
from their jaxprs; this module proves the **numeric** ones.  The same
entry set (the dp×pp×mp train step, both unified serving steps — fp32
and int8-KV — the disagg wire stage/commit, ``dist_allreduce_quant``,
the quant_matmul decode path) is traced shape-only and abstractly
interpreted over a precision lattice: every value carries a *storage
format* (its dtype), a *kind* on the quantization ladder, and a
*scale-provenance* set naming the quantize/rescale/scatter-max events
its bytes were produced under.  Five rule families fire on the
propagated environment:

   TPL300 format-legality  a storage format unknown to the verifier, or
          a known format flowing into an op class whose backend row does
          not admit it.  fp8 lands in this codebase by *declaring* rows
          (KNOWN_FORMATS + FORMAT_LEGALITY) — until then any float8_*
          reaching a traced program is a finding, so the on-ramp is a
          table edit, not a silent pass.
   TPL301 low-precision-accumulation  a dot/conv with a sub-fp32
          operand whose result dtype is not an fp32-class accumulator;
          plus the declared ``ACCUM_DTYPE`` of every Pallas kernel
          module and every applied fusion-catalog Site — the kernel arm
          and the XLA fallback of each op must *agree* on fp32
          accumulation, and the declarations are what pins the kernel
          side (the kernels never appear in CPU traces).
   TPL302 silent-upcast-x64-drift  float64 anywhere in a traced
          program: an f64 entry operand, or an eqn whose output is f64
          with no f64 input (the upcast point).  The repo runs x64-off
          everywhere; a stray f64 doubles HBM traffic silently.
   TPL303 scale-provenance-mismatch  int8 bytes consumed (dequantized,
          rescaled, or quantized-against) under a scale that does not
          trace to the same quantize/rescale/kv_scale_update event that
          produced the bytes.  This is exactly the PR 8 pre-fix bug —
          a reused KV page dequantized against the prior tenant's
          absmax — rebuilt on demand via the
          ``ServingEngine._zero_scale_on_alloc`` hook
          (:func:`build_admit_entry` with ``zero_scale_on_alloc=False``)
          where it must fire exactly once; the shipped tree is clean.
   TPL304 unclamped-scale-divide  a divide by a scale that is not
          dominated by a ``maximum(., SCALE_EPS)`` clamp
          (ops/quant.py::SCALE_EPS) — the zero-row NaN factory.
   TPL305 double-quantization  re-quantizing bytes that are already
          int8 (or their raw float view) without an intervening
          dequantize/rescale — each pass multiplies the rounding error.

The interpreter recurses into scan/remat2/pjit/shard_map/custom-vjp
bodies exactly as shardcheck does (scan carries run a 2-sweep
fixpoint), and baseline/EXPLAINED/diff semantics mirror shardcheck:
``python -m tools.lint --quantcheck`` with exit codes 0 clean / 1
findings-or-drift / 2 usage / 3 missing baseline, drift-checked against
``artifacts/quantcheck.json``.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field, replace

from .core import Finding
from .shardcheck import (COLLECTIVE_PRIMS, _eqn_location, _flatten_names,
                         _inner_closed, _count_eqns, _finding_entry, _jax,
                         load_baseline, write_baseline)

__all__ = [
    "QVal",
    "QuantEntry",
    "QuantInterp",
    "EXPLAINED",
    "KNOWN_FORMATS",
    "FORMAT_LEGALITY",
    "QUANTCHECK_RULES",
    "PALLAS_KERNEL_MODULES",
    "build_admit_entry",
    "build_entries",
    "build_report",
    "check_entry",
    "diff_baselines",
    "format_environment",
    "kernel_decl_findings",
    "load_baseline",
    "regression_report",
    "site_accum_findings",
    "stale_explanations",
    "unexplained_findings",
    "write_baseline",
]

QUANTCHECK_RULES = {
    "TPL300": "format-legality",
    "TPL301": "low-precision-accumulation",
    "TPL302": "silent-upcast-x64-drift",
    "TPL303": "scale-provenance-mismatch",
    "TPL304": "unclamped-scale-divide",
    "TPL305": "double-quantization",
}

# ---------------------------------------------------------------------------
# the format-legality table (TPL300)
# ---------------------------------------------------------------------------
# Formats the verifier understands.  A dtype outside this set (float8_*,
# int4, ...) reaching any traced program is a TPL300 finding: a new
# storage format lands by adding it here AND adding it to the legality
# rows of every op class that may carry it — the fp8 on-ramp is these
# two table edits plus whatever kernels make them true.
KNOWN_FORMATS = frozenset({
    "float32", "float64", "bfloat16", "float16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool", "float0",
})
# Extended dtypes that are opaque-but-fine (new-style PRNG keys).
_KNOWN_PREFIXES = ("key<",)

BACKEND = "tpu"

# (backend, op class) -> formats that class may legally carry.  Op
# classes are the places a format commitment is load-bearing: the MXU
# contraction units (dot/conv), the ICI collectives, and the
# scatter/gather paths the paged-KV plane lives on.
_WIDE = frozenset({
    "float32", "float64", "bfloat16", "float16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
})
FORMAT_LEGALITY = {
    (BACKEND, "dot"): frozenset({
        "float32", "float64", "bfloat16", "float16", "int8", "int32"}),
    (BACKEND, "conv"): frozenset({
        "float32", "float64", "bfloat16", "float16", "int8", "int32"}),
    (BACKEND, "collective"): _WIDE,
    (BACKEND, "scatter"): _WIDE,
    (BACKEND, "gather"): _WIDE,
}

# Sub-fp32 storage formats: a dot/conv touching one of these must
# accumulate into an fp32-class dtype (TPL301).  float8_* is matched by
# prefix so the rule is already correct the day fp8 rows are declared.
SUB_F32 = frozenset({"bfloat16", "float16", "int8", "uint8", "int16"})
_ACCUM_OK = frozenset({"float32", "float64", "int32"})

# Pallas kernel modules that must declare ``ACCUM_DTYPE``.  CPU traces
# only ever contain the XLA fallback arms (tiny geometries fail the
# *_supported gates), so the kernel side of the "both arms accumulate
# fp32" contract is pinned by these declarations instead.
PALLAS_KERNEL_MODULES = (
    "paddle_tpu.ops.pallas.decode_attention",
    "paddle_tpu.ops.pallas.flash_attention",
    "paddle_tpu.ops.pallas.fused_ce",
    "paddle_tpu.ops.pallas.lora_matmul",
    "paddle_tpu.ops.pallas.quant_matmul",
    "paddle_tpu.ops.pallas.ragged_paged_attention",
)

# Known findings with rationales, keyed (entry, rule) — the shardcheck
# EXPLAINED analog.  A finding keyed here is reported in the baseline
# but does not fail the run; a key with no matching finding is itself
# drift (stale rationales must be pruned like stale suppressions).
EXPLAINED = {
    ("train_dp2_pp2_mp2", "TPL301"):
        "the GPT blocks' bf16->bf16 matmuls are deliberate (models/"
        "gpt.py block comment): the TPU MXU accumulates bf16 dots in "
        "fp32 internally regardless of the emitted dtype, and bf16 "
        "outputs halve the residuals' HBM traffic; the rule stays on "
        "so a NEW sub-fp32 dot in any other entry still fails the gate",
}


def _known_fmt(f) -> bool:
    if f is None:
        return True            # no dtype (tokens/effects) — not a format
    return f in KNOWN_FORMATS or any(f.startswith(p)
                                     for p in _KNOWN_PREFIXES)


def _fmt(aval):
    d = getattr(aval, "dtype", None)
    return str(d) if d is not None else None


# ---------------------------------------------------------------------------
# the precision lattice
# ---------------------------------------------------------------------------

# Kind ladder, ordered by join priority (higher wins a merge — once
# bytes are quantized, forgetting that is the unsafe direction):
#   data   plain numeric value
#   abs    an |x| reduction on the way to becoming a scale
#   scale  a dequantization scale (fp32, one per page/channel/chunk)
#   ratio  old_scale / new_scale — the rescale_int8 multiplier
#   qpend  value / scale, not yet rounded to int8 (quantize in flight)
#   raw    the float view of int8 bytes (int8 -> float convert); still
#          carries the bytes' provenance until a scale multiply lands
#   quant  int8 bytes
_KIND_PRIO = {"data": 0, "abs": 1, "scale": 2, "ratio": 3,
              "qpend": 4, "raw": 5, "quant": 6}

# maximum(x, lit) marks x clamped when lit is a tiny positive floor
# (SCALE_EPS = 1e-30; anything <= this bound reads as an epsilon clamp,
# not a data max).
_CLAMP_LIT_MAX = 1e-6


@dataclass(frozen=True)
class QVal:
    """One abstract value: storage format, quantization kind, and scale
    provenance.

    ``origin`` is the id of the scale event (a quantize / rescale /
    scatter-max / scale-plane invar) this value's scale derives from;
    ``anc`` is the full ancestor event set (lineage through rescales and
    running-absmax updates).  ``foreign`` marks a scale plane that may
    hold a *prior tenant's* absmax (the admit entry's invar plane) —
    consuming it without an intervening reset is TPL303.  ``clamped``
    records domination by a ``maximum(., SCALE_EPS)``; ``rfrom`` is, for
    a ratio, the lineage of the OLD scale (the bytes it may legally
    rescale); ``lit`` carries scalar literal values (127.0 / 0.0 /
    SCALE_EPS recognition)."""

    fmt: str | None = "float32"
    kind: str = "data"
    origin: int = -1
    anc: frozenset = frozenset()
    foreign: bool = False
    clamped: bool = False
    rfrom: frozenset = frozenset()
    lit: float | None = None


def _qjoin(a: QVal, b: QVal) -> QVal:
    """Join two lattice values (select_n / concatenate / scan carry):
    the higher kind wins, lineages union, foreign is sticky, clamped
    only survives if both sides were clamped."""
    w = a if _KIND_PRIO.get(a.kind, 0) >= _KIND_PRIO.get(b.kind, 0) else b
    return replace(w, anc=a.anc | b.anc, foreign=a.foreign or b.foreign,
                   clamped=a.clamped and b.clamped,
                   rfrom=a.rfrom | b.rfrom, lit=None)


def _qval_str(q: QVal) -> str:
    """Deterministic rendering for histograms/goldens: format and kind
    plus the boolean flags — event ids are interpreter-run-relative and
    deliberately excluded."""
    s = f"{q.fmt}|{q.kind}"
    if q.clamped:
        s += "|clamped"
    if q.foreign:
        s += "|foreign"
    return s


# ---------------------------------------------------------------------------
# entry programs
# ---------------------------------------------------------------------------

@dataclass
class QuantEntry:
    """One registered program plus the quantization facts the tracer
    cannot recover from the jaxpr alone: which invars are scale planes,
    which int8 invars pair with which plane (their bytes were produced
    under that plane's events), and which planes may carry a foreign
    (prior-tenant) absmax."""

    name: str
    closed: object                        # jax ClosedJaxpr
    source: str
    invar_names: list = field(default_factory=list)
    scale_invars: set = field(default_factory=set)
    foreign_scale_invars: set = field(default_factory=set)
    page_pairs: dict = field(default_factory=dict)   # int8 idx -> scale idx


def _tiny_serving_cfg():
    import jax.numpy as jnp

    from paddle_tpu.inference.serving import LlamaConfig

    return LlamaConfig(vocab_size=128, hidden=32, n_layers=2, n_heads=2,
                       n_kv_heads=2, ffn_hidden=64, max_seq_len=64,
                       dtype=jnp.float32, param_dtype=jnp.float32)


def _tiny_engine(kv_quant: bool):
    from paddle_tpu.inference.serving import ServingEngine

    return ServingEngine(_tiny_serving_cfg(), max_batch=2, page_size=8,
                         max_seq=64, n_pages=1 + 8, kv_quant=kv_quant)


def build_train_entry() -> QuantEntry:
    """The dp×pp×mp sharded train step, reusing shardcheck's tracer (one
    trace serves both verifiers' entry registries)."""
    from .shardcheck import build_train_entry as _sc_train

    ep = _sc_train()
    return QuantEntry(name=ep.name, closed=ep.closed, source=ep.source,
                      invar_names=list(ep.invar_names))


def build_serving_fp32_entry() -> QuantEntry:
    _jax()
    import paddle_tpu  # noqa: F401  -- installs the jax_compat shims

    eng = _tiny_engine(kv_quant=False)
    closed = eng.trace_unified()
    names = (["params" + n for n in _flatten_names(eng.params)]
             + ["k_pages", "v_pages", "tokens", "prev_out", "chain_mask",
                "chain_row", "ptable", "row_slot", "pos0", "n_valid",
                "temps", "topps", "seeds"])
    return QuantEntry(name="serving_unified_fp32", closed=closed,
                      source="paddle_tpu/inference/serving.py",
                      invar_names=names)


def build_serving_int8_entry() -> QuantEntry:
    """The int8-KV unified step: the page arrays are int8 invars paired
    with their scale-plane invars — the engine's allocator maintains the
    no-foreign-scale invariant (proven separately by the admit entries),
    so the planes enter *trusted*."""
    jax = _jax()
    import paddle_tpu  # noqa: F401

    eng = _tiny_engine(kv_quant=True)
    closed = eng.trace_unified_quant()
    n = len(jax.tree_util.tree_leaves(eng.params))
    names = (["params" + s for s in _flatten_names(eng.params)]
             + ["k_pages", "v_pages", "k_scales", "v_scales", "tokens",
                "prev_out", "chain_mask", "chain_row", "ptable",
                "row_slot", "pos0", "n_valid", "temps", "topps", "seeds"])
    return QuantEntry(name="serving_unified_int8kv", closed=closed,
                      source="paddle_tpu/inference/serving.py",
                      invar_names=names,
                      scale_invars={n + 2, n + 3},
                      page_pairs={n: n + 2, n + 1: n + 3})


def build_wire_entries() -> list:
    """Disagg wire stage/commit over *int8* pages: pure byte movement —
    no scale plane travels on this path (the adoption commit ships
    scales separately), so the pages are anonymous quant values and the
    verifier proves no eqn dequantizes them en route."""
    jax = _jax()
    import numpy as np

    from paddle_tpu.inference.serving import (wire_gather_pages,
                                              wire_scatter_pages)

    eng = _tiny_engine(kv_quant=True)
    kp = eng.k_pages
    n_ship = 2
    pg = jax.ShapeDtypeStruct((n_ship,), np.int32)
    staged = jax.ShapeDtypeStruct(
        (kp.shape[0], n_ship) + kp.shape[2:], kp.dtype)
    gather = jax.make_jaxpr(wire_gather_pages)(
        jax.ShapeDtypeStruct(kp.shape, kp.dtype), pg)
    scatter = jax.make_jaxpr(wire_scatter_pages)(
        jax.ShapeDtypeStruct(kp.shape, kp.dtype), pg, staged)
    out = []
    for nm, closed, names in (
            ("wire_stage_int8", gather, ["k_pages", "page_ids"]),
            ("wire_commit_int8", scatter,
             ["k_pages", "page_ids", "staged"])):
        out.append(QuantEntry(
            name=nm, closed=closed,
            source="paddle_tpu/inference/serving.py", invar_names=names))
    return out


def build_allreduce_entry() -> QuantEntry:
    """``dist_allreduce_quant`` (int8-on-the-wire gradient sync) reusing
    shardcheck's dp2×pp2 trace.  Every property the docstring promises
    is a rule here: both quantize phases divide clamped scales (TPL304),
    the fp32 dequant-accumulate keeps int8 out of the reduction
    (TPL301/TPL305), and each chunk dequantizes against its own absmax
    event (TPL303)."""
    from .shardcheck import build_quant_entry as _sc_quant

    ep = _sc_quant()
    return QuantEntry(name=ep.name, closed=ep.closed, source=ep.source,
                      invar_names=["grads"])


def build_quant_matmul_entry() -> QuantEntry:
    """The weight-only int8 decode matmul's XLA arm (M=4 fails the MXU
    gate, so the trace is the fallback — the kernel arm is pinned by its
    ACCUM_DTYPE declaration): epilogue-dequant means the dot output
    carries raw provenance until the scale row-multiply lands."""
    jax = _jax()
    import paddle_tpu  # noqa: F401
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.quant_matmul import quant_matmul

    x = jax.ShapeDtypeStruct((4, 128), jnp.bfloat16)
    wq = jax.ShapeDtypeStruct((128, 128), jnp.int8)
    sc = jax.ShapeDtypeStruct((128,), jnp.float32)
    closed = jax.make_jaxpr(quant_matmul)(x, wq, sc)
    return QuantEntry(name="quant_matmul_decode", closed=closed,
                      source="paddle_tpu/ops/pallas/quant_matmul.py",
                      invar_names=["x", "wq", "scale"],
                      scale_invars={2}, page_pairs={1: 2})


def build_admit_entry(zero_scale_on_alloc: bool = True) -> QuantEntry:
    """The KV-admit first-write program, with the scale plane marked
    *foreign* (it may hold a prior tenant's absmax — exactly the state
    ``_alloc_pages`` hands ``kv_admit_first_write``).

    With ``zero_scale_on_alloc=True`` (shipped): the kv_scale_reset
    scatter clears the foreign bit before the running-absmax update, so
    the quantize divide is clean.  With ``False``: the PR 8 *pre-fix*
    program — the prior tenant's absmax leaks through scatter-max into
    the quantize scale and TPL303 fires, exactly once, at the
    quantize_to_scale divide."""
    jax = _jax()
    import functools

    import paddle_tpu  # noqa: F401
    import jax.numpy as jnp

    from paddle_tpu.inference.serving import kv_admit_first_write

    n_pages, n_kv, bs, d, n_write = 6, 2, 8, 16, 2
    pages = jax.ShapeDtypeStruct((n_pages, n_kv, bs, d), jnp.int8)
    scales = jax.ShapeDtypeStruct((n_pages, n_kv), jnp.float32)
    pg = jax.ShapeDtypeStruct((n_write,), jnp.int32)
    toks = jax.ShapeDtypeStruct((n_write, n_kv, bs, d), jnp.float32)
    fn = functools.partial(kv_admit_first_write,
                           _zero_scale_on_alloc=zero_scale_on_alloc)
    closed = jax.make_jaxpr(fn)(pages, scales, pg, toks)
    name = ("serving_admit_quant" if zero_scale_on_alloc
            else "serving_admit_quant_noreset")
    return QuantEntry(name=name, closed=closed,
                      source="paddle_tpu/inference/serving.py",
                      invar_names=["pages", "scales", "page_ids", "tokens"],
                      scale_invars={1}, foreign_scale_invars={1},
                      page_pairs={0: 1})


def build_entries(names=None) -> list:
    """All registered entry programs (optionally filtered by name)."""
    entries = [build_train_entry(),
               build_serving_fp32_entry(),
               build_serving_int8_entry()]
    entries += build_wire_entries()
    entries.append(build_allreduce_entry())
    entries.append(build_quant_matmul_entry())
    entries.append(build_admit_entry(zero_scale_on_alloc=True))
    if names is not None:
        entries = [e for e in entries if e.name in set(names)]
    return entries


# ---------------------------------------------------------------------------
# the abstract interpreter
# ---------------------------------------------------------------------------

_STRUCTURAL = {
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims", "rev",
    "pad", "sort", "copy", "stop_gradient", "device_put",
    "optimization_barrier", "reduce_precision", "sharding_constraint",
    "transpose",
}

_HIGHER_ORDER = {
    "pjit", "scan", "while", "cond", "remat2", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "shard_map",
}

_SCATTER_SET = {"scatter", "scatter-add", "scatter_add",
                "dynamic_update_slice"}
_SCATTER_MAX = {"scatter-max", "scatter_max", "scatter-min", "scatter_min"}


def _is_float(fmt) -> bool:
    return fmt is not None and (fmt.startswith("float")
                                or fmt == "bfloat16") and fmt != "float0"


def _is_sub_f32(fmt) -> bool:
    return fmt is not None and (fmt in SUB_F32 or fmt.startswith("float8"))


class QuantInterp:
    """Propagates QVals through one entry program and collects rule
    events.  One instance per entry; findings accumulate on
    ``self.findings`` (deduplicated by (rule, path, line) so the scan
    2-sweep fixpoint cannot double-report) and the rendered-value
    histogram (for the golden format-environment test) on
    ``self.all_fmts``."""

    def __init__(self, entry: QuantEntry):
        self.entry = entry
        self.findings: list[Finding] = []
        self.all_fmts: dict[str, int] = {}
        self.in_vals: list[QVal] = []
        self.out_vals: list[QVal] = []
        self._seen: set = set()
        self._nev = 0

    # -- bookkeeping --------------------------------------------------------

    def _event(self) -> int:
        e = self._nev
        self._nev += 1
        return e

    def _finding(self, rule, eqn, message, severity="error", key=None):
        path, line = _eqn_location(eqn) if eqn is not None else (None, 0)
        k = key if key is not None else (rule, path, line)
        if k in self._seen:
            return
        self._seen.add(k)
        self.findings.append(Finding(
            rule=rule, name=QUANTCHECK_RULES[rule], severity=severity,
            path=path or self.entry.source, line=line or 1, col=0,
            message=f"[entry {self.entry.name}] {message}"))

    def _record(self, q: QVal):
        s = _qval_str(q)
        self.all_fmts[s] = self.all_fmts.get(s, 0) + 1

    @staticmethod
    def _read(env, atom) -> QVal:
        if type(atom).__name__ == "Literal":
            lit = None
            try:
                v = atom.val
                if getattr(v, "shape", ()) in ((), (1,)):
                    lit = float(v)
            except Exception:
                lit = None
            return QVal(fmt=_fmt(atom.aval), lit=lit)
        return env.get(atom, QVal(fmt=_fmt(atom.aval)))

    # -- driver -------------------------------------------------------------

    def run(self):
        jaxpr = self.entry.closed.jaxpr
        env = {}
        for cv in jaxpr.constvars:
            env[cv] = QVal(fmt=_fmt(cv.aval))
        # first pass: scale planes get their root events...
        pair_event = {}
        for i, v in enumerate(jaxpr.invars):
            fmt = _fmt(v.aval)
            if i in self.entry.scale_invars:
                e = self._event()
                pair_event[i] = e
                env[v] = QVal(fmt=fmt, kind="scale", origin=e,
                              anc=frozenset({e}),
                              foreign=i in self.entry.foreign_scale_invars)
        # ...then int8 invars pair with them (or get anonymous events)
        for i, v in enumerate(jaxpr.invars):
            if v in env:
                continue
            fmt = _fmt(v.aval)
            if fmt in ("int8", "uint8"):
                if i in self.entry.page_pairs:
                    e = pair_event[self.entry.page_pairs[i]]
                else:
                    e = self._event()
                env[v] = QVal(fmt=fmt, kind="quant", origin=e,
                              anc=frozenset({e}))
            else:
                env[v] = QVal(fmt=fmt)
            if fmt == "float64":
                nm = (self.entry.invar_names[i]
                      if i < len(self.entry.invar_names) else f"#{i}")
                self._finding(
                    "TPL302", None,
                    f"entry operand '{nm}' is float64; this repo runs "
                    "x64-off — an f64 operand doubles HBM traffic and "
                    "forces every consumer to upcast silently",
                    key=("TPL302", "invar", i))
        self.in_vals = [env[v] for v in jaxpr.invars]
        for q in self.in_vals:
            self._record(q)
        self._interp(jaxpr, env)
        self.out_vals = [self._read(env, v) for v in jaxpr.outvars]
        return self

    # -- interpretation -----------------------------------------------------

    def _interp(self, jaxpr, env):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            ins = [self._read(env, a) for a in eqn.invars]
            self._check_formats(eqn)
            self._check_upcast(eqn)
            if name in _HIGHER_ORDER:
                if name == "scan":
                    outs = self._do_scan(eqn, ins)
                else:
                    outs = self._do_body(eqn, ins)
            else:
                outs = self._transfer(eqn, ins)
            for v, q in zip(eqn.outvars, outs):
                if type(v).__name__ == "DropVar":
                    continue
                env[v] = q
                self._record(q)

    def _run_body(self, jaxpr, in_states):
        env = {}
        for cv in jaxpr.constvars:
            env[cv] = QVal(fmt=_fmt(cv.aval))
        for v, st in zip(jaxpr.invars, in_states):
            env[v] = st
        self._interp(jaxpr, env)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _do_scan(self, eqn, ins):
        p = eqn.params
        inner = p["jaxpr"].jaxpr
        nc, ncarry = p["num_consts"], p["num_carry"]
        const_in = ins[:nc]
        carry = list(ins[nc:nc + ncarry])
        xs = ins[nc + ncarry:]
        outs = None
        for _ in range(2):                     # carry fixpoint (2 sweeps)
            outs = self._run_body(inner, const_in + carry + xs)
            carry = [_qjoin(a, b) for a, b in zip(carry, outs[:ncarry])]
        return carry + outs[ncarry:]

    def _do_body(self, eqn, ins):
        """Generic higher-order handler (pjit/while/cond/remat/custom-
        vjp/shard_map): run every body with the trailing-aligned operand
        states and join the results — QVals are shape-agnostic, so no
        per-dim bookkeeping is needed."""
        bodies = _inner_closed(eqn)
        if not bodies:
            return self._transfer(eqn, ins)
        results = None
        for inner, _consts in bodies:
            states = list(ins)
            if eqn.primitive.name == "cond":
                states = states[1:]            # predicate operand
            n = len(inner.invars)
            if len(states) > n:
                states = states[-n:]
            while len(states) < n:
                states.insert(0, QVal())
            outs = self._run_body(inner, states)
            if results is None:
                results = outs
            else:
                results = [_qjoin(a, b) for a, b in zip(results, outs)]
        n_out = len(eqn.outvars)
        results = (results or [])[:n_out]
        while len(results) < n_out:
            results.append(QVal())
        return [replace(q, fmt=_fmt(v.aval))
                for q, v in zip(results, eqn.outvars)]

    # -- per-eqn rule checks ------------------------------------------------

    def _check_formats(self, eqn):
        name = eqn.primitive.name
        for a in list(eqn.invars) + list(eqn.outvars):
            if type(a).__name__ == "DropVar":
                continue
            f = _fmt(a.aval)
            if not _known_fmt(f):
                self._finding(
                    "TPL300", eqn,
                    f"unknown storage format '{f}' in '{name}'; declare "
                    "it in quantcheck.KNOWN_FORMATS and add it to the "
                    "FORMAT_LEGALITY rows of every op class that may "
                    "carry it (this is how fp8 lands)",
                    key=("TPL300", "fmt", f))
        if name in ("dot_general",):
            opclass = "dot"
        elif name == "conv_general_dilated":
            opclass = "conv"
        elif name in COLLECTIVE_PRIMS:
            opclass = "collective"
        elif name in _SCATTER_SET or name in _SCATTER_MAX:
            opclass = "scatter"
        elif name in ("gather", "dynamic_slice"):
            opclass = "gather"
        else:
            return
        legal = FORMAT_LEGALITY.get((BACKEND, opclass))
        if not legal:
            self._finding(
                "TPL300", eqn,
                f"no FORMAT_LEGALITY row for backend '{BACKEND}' op "
                f"class '{opclass}' — declare one",
                key=("TPL300", "row", opclass))
            return
        for a in eqn.invars:
            f = _fmt(a.aval)
            if f is not None and _known_fmt(f) and f not in legal:
                self._finding(
                    "TPL300", eqn,
                    f"format '{f}' is not declared legal for op class "
                    f"'{opclass}' on backend '{BACKEND}' (legal: "
                    f"{sorted(legal)}); extend the FORMAT_LEGALITY row "
                    "or keep the format off this path",
                    key=("TPL300", opclass, f))

    def _check_upcast(self, eqn):
        outs = [v for v in eqn.outvars if type(v).__name__ != "DropVar"]
        if not any(_fmt(v.aval) == "float64" for v in outs):
            return
        if any(_fmt(a.aval) == "float64" for a in eqn.invars):
            return
        self._finding(
            "TPL302", eqn,
            f"'{eqn.primitive.name}' produces float64 from non-f64 "
            "inputs — a silent x64 upcast point; this repo runs x64-off "
            "(check for python-float promotion or an explicit "
            "astype(float64))")

    def _check_dot_accum(self, eqn, ins):
        sub = [q.fmt for q in ins[:2] if _is_sub_f32(q.fmt)]
        if not sub:
            return
        out_fmt = _fmt(eqn.outvars[0].aval)
        if out_fmt in _ACCUM_OK:
            return
        self._finding(
            "TPL301", eqn,
            f"'{eqn.primitive.name}' contracts sub-fp32 operand(s) "
            f"{sorted(set(sub))} into a {out_fmt} result — accumulation "
            "happens below fp32; set "
            "preferred_element_type=jnp.float32 on the dot (both the "
            "kernel arm and this XLA arm must accumulate fp32)")

    # -- the transfer function ----------------------------------------------

    def _transfer(self, eqn, ins):
        name = eqn.primitive.name
        outs = eqn.outvars

        def mk(q: QVal):
            return [replace(q, fmt=_fmt(v.aval)) for v in outs]

        a = ins[0] if ins else QVal()
        b = ins[1] if len(ins) > 1 else None

        if name == "abs":
            return mk(replace(a, kind="abs") if a.kind == "data" else a)
        if name in ("reduce_max", "reduce_min", "reduce_sum",
                    "reduce_prod", "cumsum", "cummax", "cummin",
                    "cumprod", "cumlogsumexp"):
            return mk(a)
        if name == "max" and b is not None:
            for x, y in ((a, b), (b, a)):
                if (y.lit is not None and 0.0 < y.lit <= _CLAMP_LIT_MAX
                        and x.kind in ("scale", "abs")):
                    return mk(replace(x, clamped=True))
            return mk(_qjoin(a, b))
        if name == "div" and b is not None:
            return mk(self._div(eqn, a, b))
        if name == "mul" and b is not None:
            return mk(self._mul(eqn, a, b))
        if name in ("round", "nextafter", "sign"):
            return mk(a)
        if name == "clamp":
            return mk(ins[1] if len(ins) > 2 else a)
        if name == "convert_element_type":
            return self._convert(eqn, a)
        if name in ("dot_general", "conv_general_dilated"):
            self._check_dot_accum(eqn, ins)
            prov = [q for q in ins[:2] if q.kind in ("quant", "raw")]
            if prov:
                anc = frozenset().union(*[q.anc for q in prov])
                return mk(QVal(kind="raw", origin=prov[0].origin, anc=anc,
                               foreign=any(q.foreign for q in prov)))
            return mk(QVal())
        if name in _SCATTER_MAX:
            u = ins[2] if len(ins) > 2 else (b or a)
            if a.kind == "scale" or u.kind == "scale":
                # running-absmax update: a fresh scale event whose
                # lineage unions the plane's and the update's — foreign
                # propagates (scatter-max cannot launder a leaked scale)
                e = self._event()
                return mk(QVal(kind="scale", origin=e,
                               anc=a.anc | u.anc | {e},
                               foreign=a.foreign or u.foreign,
                               clamped=a.clamped and u.clamped))
            return mk(_qjoin(a, u))
        if name in _SCATTER_SET:
            u = ins[1] if name == "dynamic_update_slice" else (
                ins[2] if len(ins) > 2 else (b or a))
            if a.kind == "scale" and u.lit == 0.0:
                # kv_scale_reset: overwriting plane entries with 0.0
                # severs provenance AND clears the foreign bit — the
                # prior tenant's absmax is gone
                e = self._event()
                return mk(QVal(kind="scale", origin=e, anc=frozenset({e}),
                               clamped=a.clamped))
            if a.kind == "quant" or u.kind == "quant":
                qs = [q for q in (a, u) if q.kind == "quant"]
                origin = u.origin if u.kind == "quant" else a.origin
                return mk(QVal(kind="quant", origin=origin,
                               anc=a.anc | u.anc,
                               foreign=any(q.foreign for q in qs)))
            if a.kind == "scale" or u.kind == "scale":
                return mk(replace(_qjoin(a, u), kind="scale"))
            return mk(_qjoin(a, u))
        if name in ("gather", "take", "dynamic_slice", "slice",
                    "take_along_axis", "argmax", "argmin"):
            return mk(replace(a, lit=None))
        if name in _STRUCTURAL:
            return mk(a)
        if name in ("concatenate", "select_n"):
            parts = ins[1:] if name == "select_n" and len(ins) > 1 else ins
            q = parts[0]
            for other in parts[1:]:
                q = _qjoin(q, other)
            return mk(q)
        if name in COLLECTIVE_PRIMS:
            return mk(a)
        # default: elementwise-style priority join
        q = a
        for other in ins[1:]:
            q = _qjoin(q, other)
        return mk(replace(q, lit=None))

    def _div(self, eqn, a: QVal, b: QVal) -> QVal:
        if a.kind == "scale" and b.kind == "scale":
            # rescale_int8's ratio = old / max(new, EPS): remembers the
            # OLD lineage (rfrom) — the bytes it may legally rescale
            if not b.clamped:
                self._tpl304(eqn, b)
            return QVal(kind="ratio", origin=b.origin, anc=a.anc | b.anc,
                        foreign=a.foreign or b.foreign, rfrom=a.anc)
        if b.kind == "scale":
            if not b.clamped:
                self._tpl304(eqn, b)
            if a.kind in ("quant", "raw"):
                self._finding(
                    "TPL305", eqn,
                    "dividing already-quantized bytes by a scale "
                    "re-quantizes them without an intervening "
                    "dequantize/rescale — each pass multiplies the "
                    "rounding error; dequantize first (or use "
                    "rescale_int8, whose ratio multiply is exact for "
                    "unchanged scales)")
            if b.foreign:
                self._finding(
                    "TPL303", eqn,
                    "quantizing against a scale that may still hold a "
                    "prior tenant's absmax (the scale plane was not "
                    "reset on page alloc) — a leaked larger scale "
                    "silently crushes this tenant's resolution; reset "
                    "the plane first (kv_scale_reset / "
                    "_zero_scale_on_alloc)")
            return QVal(kind="qpend", origin=b.origin, anc=a.anc | b.anc,
                        foreign=b.foreign)
        if a.kind == "abs" and b.lit is not None and b.lit == 127.0:
            # |x|max / 127: a fresh scale is born here
            e = self._event()
            return QVal(kind="scale", origin=e, anc=a.anc | {e},
                        foreign=a.foreign)
        return replace(_qjoin(a, b), lit=None)

    def _mul(self, eqn, a: QVal, b: QVal) -> QVal:
        for x, y in ((a, b), (b, a)):
            if x.kind in ("raw", "qpend") and y.kind == "scale":
                # dequant: bytes * scale — lineages must intersect
                if y.foreign or (x.anc and y.anc and not (x.anc & y.anc)):
                    self._finding(
                        "TPL303", eqn,
                        "dequantizing bytes against a scale from a "
                        f"different event lineage (bytes {sorted(x.anc)}"
                        f" vs scale {sorted(y.anc)}"
                        f"{', foreign plane' if y.foreign else ''}) — "
                        "the bytes were not produced under this scale; "
                        "thread the scale from the same "
                        "quantize/rescale/kv_scale_update event")
                return QVal()
            if x.kind == "raw" and y.kind == "ratio":
                # rescale: the ratio's OLD lineage must cover the bytes
                if x.anc and y.rfrom and not (x.anc & y.rfrom):
                    self._finding(
                        "TPL303", eqn,
                        "rescaling bytes with a ratio whose old-scale "
                        f"lineage {sorted(y.rfrom)} does not cover the "
                        f"bytes' lineage {sorted(x.anc)} — the ratio "
                        "was computed from a different page/chunk's "
                        "scale history")
                return QVal(kind="qpend", origin=y.origin,
                            anc=x.anc | y.anc,
                            foreign=x.foreign or y.foreign)
        return replace(_qjoin(a, b), lit=None)

    def _convert(self, eqn, a: QVal):
        outs = eqn.outvars
        out_fmt = _fmt(outs[0].aval)
        q = a
        if a.kind == "qpend" and out_fmt in ("int8", "uint8"):
            q = replace(a, kind="quant", lit=None)
        elif a.kind == "quant" and _is_float(out_fmt):
            # the raw float view of int8 bytes: provenance sticks until
            # a scale multiply lands (dequant) — TPL305 guards the
            # re-quantize path, TPL303 the wrong-scale path
            q = replace(a, kind="raw", lit=None)
        elif (a.kind == "data" and out_fmt in ("int8", "uint8")
              and _is_float(a.fmt)):
            # float -> int8 with no scale divide in sight: an anonymous
            # quantization event (legal, but its scale is untracked)
            e = self._event()
            q = QVal(kind="quant", origin=e, anc=frozenset({e}))
        return [replace(q, fmt=_fmt(v.aval)) for v in outs]

    def _tpl304(self, eqn, b: QVal):
        self._finding(
            "TPL304", eqn,
            "divide by a scale that is not dominated by a "
            "maximum(., SCALE_EPS) clamp (ops/quant.py::SCALE_EPS) — a "
            "zero row yields a 0.0 scale and this divide mints "
            "NaN/inf; clamp the scale first")


# ---------------------------------------------------------------------------
# declaration-side rules (TPL301 outside the traces)
# ---------------------------------------------------------------------------

def site_accum_findings(entry_name: str, sites) -> list:
    """TPL301 over the fusion catalog: every *applied* Site must declare
    an fp32-class ``accum_dtype`` — the per-site analog of the kernel
    module declarations (a fused replacement that accumulated below
    fp32 would pass the trace check, which only sees the unfused XLA
    arm)."""
    out = []
    for s in sites:
        if not getattr(s, "applied", False):
            continue
        acc = getattr(s, "accum_dtype", "float32")
        if acc not in ("float32", "float64"):
            out.append(Finding(
                rule="TPL301", name=QUANTCHECK_RULES["TPL301"],
                severity="error", path="paddle_tpu/compiler/catalog.py",
                line=1, col=0,
                message=(f"[entry {entry_name}] applied fusion site "
                         f"'{getattr(s, 'template', '?')}' declares "
                         f"accum_dtype={acc!r} — fused kernels must "
                         "accumulate in fp32 like the XLA arms they "
                         "replace")))
    return out


def kernel_decl_findings() -> tuple:
    """(findings, declarations) for every Pallas kernel module's
    ``ACCUM_DTYPE``.  A module missing the declaration, or declaring a
    sub-fp32 accumulator, is TPL301: the kernel arms never appear in
    CPU traces, so the declaration is the only statically checkable
    handle on their accumulation dtype."""
    import importlib

    out, decls = [], {}
    for mod in PALLAS_KERNEL_MODULES:
        path = mod.replace(".", "/") + ".py"
        try:
            m = importlib.import_module(mod)
            acc = getattr(m, "ACCUM_DTYPE", None)
        except Exception as e:  # pragma: no cover - import errors are
            # environment problems, not precision findings
            out.append(Finding(
                rule="TPL301", name=QUANTCHECK_RULES["TPL301"],
                severity="warning", path=path, line=1, col=0,
                message=(f"[entry kernel_decls] could not import {mod}: "
                         f"{type(e).__name__}: {e}")))
            decls[mod] = None
            continue
        decls[mod] = acc
        if acc not in ("float32", "float64"):
            out.append(Finding(
                rule="TPL301", name=QUANTCHECK_RULES["TPL301"],
                severity="error", path=path, line=1, col=0,
                message=(f"[entry kernel_decls] kernel module {mod} "
                         f"declares ACCUM_DTYPE={acc!r} (expected "
                         "'float32'/'float64'); every Pallas kernel "
                         "accumulates in an fp32 scratch — declare it "
                         "so the verifier can hold both arms to the "
                         "same contract")))
    return out, decls


# ---------------------------------------------------------------------------
# report / baseline
# ---------------------------------------------------------------------------

def check_entry(entry: QuantEntry) -> tuple:
    """(interp, findings) for one entry: lattice propagation plus the
    per-entry fusion-site accumulation check."""
    interp = QuantInterp(entry).run()
    findings = list(interp.findings)
    try:
        from paddle_tpu.compiler.fusion_pass import plan_closed

        plan = plan_closed(entry.closed)
        findings += site_accum_findings(entry.name, plan.walk())
    except Exception as e:  # pragma: no cover - planner bugs must not
        # kill the verifier
        findings.append(Finding(
            rule="TPL301", name=QUANTCHECK_RULES["TPL301"],
            severity="warning", path=entry.source, line=1, col=0,
            message=f"[entry {entry.name}] fusion planning failed: "
                    f"{type(e).__name__}: {e}"))
    return interp, findings


def format_environment(entry: QuantEntry) -> dict:
    """Deterministic summary of the derived per-var format environment —
    the golden test pins this for the int8 serving step."""
    interp = QuantInterp(entry).run()
    invars = {}
    for name, q in zip(entry.invar_names, interp.in_vals):
        invars[name] = _qval_str(q)
    return {
        "entry": entry.name,
        "invars": invars,
        "outvars": [_qval_str(q) for q in interp.out_vals],
        "format_histogram": dict(sorted(interp.all_fmts.items())),
    }


def _entry_digest(interp: QuantInterp) -> str:
    blob = json.dumps(
        {"fmts": dict(sorted(interp.all_fmts.items())),
         "outs": [_qval_str(q) for q in interp.out_vals]},
        sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def build_report(names=None) -> dict:
    """Run every registered entry plus the declaration-side checks;
    returns findings + the baseline payload."""
    entries = build_entries(names)
    findings: list[Finding] = []
    payload: dict = {"version": 1, "entries": {}}
    for entry in entries:
        interp, fs = check_entry(entry)
        findings += fs
        counts: dict = {}
        for f in fs:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        payload["entries"][entry.name] = {
            "source": entry.source,
            "n_eqns": _count_eqns(entry.closed.jaxpr),
            "formats": sorted(set(interp.all_fmts)),
            "findings": dict(sorted(counts.items())),
            "fmt_digest": _entry_digest(interp),
        }
    kfs, decls = kernel_decl_findings()
    findings += kfs
    payload["kernel_accum"] = decls
    payload["explained"] = sorted([k, r] for (k, r) in EXPLAINED)
    return {"findings": findings, "baseline": payload}


def regression_report() -> dict:
    """The TPL303 regression harness: the *pre-fix* admit program
    (``_zero_scale_on_alloc=False``) must produce exactly one TPL303 —
    the prior tenant's absmax leaking into the reused page's quantize —
    and the shipped program exactly zero.  ``ok`` is the CI gate's
    pass/fail."""
    out: dict = {}
    for label, flag in (("regression", False), ("shipped", True)):
        entry = build_admit_entry(zero_scale_on_alloc=flag)
        interp = QuantInterp(entry).run()
        t303 = [f for f in interp.findings if f.rule == "TPL303"]
        out[label] = {
            "entry": entry.name,
            "tpl303": len(t303),
            "messages": [f"{f.path}:{f.line} {f.message}" for f in t303],
        }
    out["ok"] = (out["regression"]["tpl303"] == 1
                 and out["shipped"]["tpl303"] == 0)
    return out


def unexplained_findings(findings: list) -> list:
    return [f for f in findings
            if (_finding_entry(f), f.rule) not in EXPLAINED]


def stale_explanations(findings: list) -> list:
    """EXPLAINED keys with no matching finding — stale rationales are
    drift, exactly like a suppression on dead code."""
    seen = {(_finding_entry(f), f.rule) for f in findings}
    return sorted(f"stale explanation: entry '{k}' rule {r} no longer "
                  "fires — prune it from quantcheck.EXPLAINED"
                  for (k, r) in EXPLAINED if (k, r) not in seen)


def diff_baselines(current: dict, base: dict) -> list:
    """Human-readable drift lines, shardcheck.diff_baselines-style."""
    out = []
    cur_e = current.get("entries", {})
    base_e = base.get("entries", {})
    for name in sorted(set(cur_e) | set(base_e)):
        a, b = cur_e.get(name), base_e.get(name)
        if a is None:
            out.append(f"entry '{name}': removed (in baseline only)")
            continue
        if b is None:
            out.append(f"entry '{name}': new (not in baseline)")
            continue
        for key in ("source", "n_eqns", "formats", "findings",
                    "fmt_digest"):
            if a.get(key) != b.get(key):
                out.append(f"entry '{name}': {key} drifted: "
                           f"{b.get(key)!r} -> {a.get(key)!r}")
    if current.get("kernel_accum") != base.get("kernel_accum"):
        out.append("kernel_accum drifted: "
                   f"{base.get('kernel_accum')!r} -> "
                   f"{current.get('kernel_accum')!r}")
    if current.get("explained") != base.get("explained"):
        out.append("explained set drifted: "
                   f"{base.get('explained')!r} -> "
                   f"{current.get('explained')!r}")
    return out
