"""tpu-lint checker framework: findings, suppressions, file contexts.

The analysis model is deliberately small: every checker is an
``ast.NodeVisitor`` fed one parsed file at a time via :meth:`Checker.check`,
plus an optional :meth:`Checker.finalize` hook that runs once after the
whole file set has been visited — that is where project-wide rules
(duplicate op registrations, never-read flags) report, since they cannot
be decided from a single file.

Suppressions are source comments, pylint-style:

    x = float(t)  # tpu-lint: disable=TPL001 -- why this is safe

A ``disable=`` comment suppresses the named rules (id ``TPL001`` or slug
``host-sync-in-trace``, comma-separated, or ``all``) for every finding
whose reported node overlaps that physical line — so a trailing comment
anywhere inside a multi-line call suppresses the whole call.  A
``disable-file=`` comment suppresses the rules for the entire file.
Everything after ``--`` is the human rationale and is ignored by the
matcher (but please write one).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "FileContext",
    "Checker",
    "Suppressions",
    "parse_file",
]

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)"
)


@dataclass
class Finding:
    """One diagnostic: rule id + slug, severity, location, message."""

    rule: str          # "TPL001"
    name: str          # "host-sync-in-trace"
    severity: str      # "error" | "warning"
    path: str          # repo-relative posix path
    line: int          # 1-based, node start
    col: int           # 0-based
    message: str
    end_line: int = 0  # node end (for multi-line suppression matching)

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "name": self.name,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)


class Suppressions:
    """Per-file map of ``tpu-lint: disable`` comments.

    Built from the token stream (not the AST) so comments on blank lines
    and trailing comments are both seen.
    """

    def __init__(self):
        self.by_line: dict[int, set[str]] = {}
        self.file_level: set[str] = set()

    @classmethod
    def scan(cls, source: str) -> "Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                kind, raw = m.group(1), m.group(2)
                # strip the optional "-- rationale" tail and whitespace
                rules = {
                    r.strip()
                    for r in raw.split("--")[0].split(",")
                    if r.strip()
                }
                if kind == "disable-file":
                    sup.file_level |= rules
                else:
                    sup.by_line.setdefault(tok.start[0], set()).update(rules)
        except (tokenize.TokenError, IndentationError):
            pass  # parse-level problems are reported separately
        return sup

    def matches(self, finding: Finding) -> bool:
        keys = {finding.rule, finding.name, "all"}
        if self.file_level & keys:
            return True
        end = max(finding.end_line, finding.line)
        for ln in range(finding.line, end + 1):
            if self.by_line.get(ln, set()) & keys:
                return True
        return False


@dataclass
class FileContext:
    """Everything a checker may want to know about the file under analysis."""

    path: str                  # repo-relative posix path
    tree: ast.AST
    source: str
    suppressions: Suppressions = field(default_factory=Suppressions)


def parse_file(path: str, display_path: str) -> tuple[FileContext | None, Finding | None]:
    """Parse one file; returns (context, None) or (None, parse-error finding)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=display_path)
    except (OSError, SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", None) or 1
        return None, Finding(
            rule="TPL000",
            name="parse-error",
            severity="error",
            path=display_path,
            line=line,
            col=0,
            message=f"could not parse file: {e}",
        )
    return FileContext(display_path, tree, source, Suppressions.scan(source)), None


class Checker(ast.NodeVisitor):
    """Base class for one lint rule.

    Subclasses set ``rule`` / ``name`` / ``severity`` / ``description``
    and implement the usual ``visit_*`` methods, calling :meth:`report`
    on violations.  State that must span files (registries, read-sets)
    lives on the instance; :meth:`finalize` turns it into findings after
    the last file.
    """

    rule = "TPL999"
    name = "unnamed"
    severity = "error"
    description = ""

    def __init__(self):
        self.findings: list[Finding] = []
        self.ctx: FileContext | None = None

    # -- driver API ---------------------------------------------------------

    def check(self, ctx: FileContext) -> None:
        self.ctx = ctx
        self.visit(ctx.tree)
        self.ctx = None

    def finalize(self) -> None:
        """Emit project-wide findings (after every file was visited)."""

    # -- helpers for subclasses ---------------------------------------------

    def report(self, node: ast.AST, message: str, *, path: str | None = None,
               line: int | None = None) -> None:
        self.findings.append(Finding(
            rule=self.rule,
            name=self.name,
            severity=self.severity,
            path=path if path is not None else self.ctx.path,
            line=line if line is not None else getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) if node is not None else 0,
            message=message,
            end_line=getattr(node, "end_lineno", 0) or 0,
        ))


# -- shared AST utilities ----------------------------------------------------

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, best effort: ``jax.lax.psum`` -> same,
    ``f()`` -> ``f``; anything non-name-like -> ''."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def names_in(node: ast.AST) -> set[str]:
    """All bare Name identifiers loaded anywhere inside an expression."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def str_constants(node: ast.AST) -> set[str]:
    """All string literals anywhere inside a node."""
    return {
        n.value
        for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }
