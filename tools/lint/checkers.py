"""tpu-lint rules: the failure modes this codebase has actually shipped.

Each checker encodes one class of bug from the round postmortems:

TPL001 host-sync-in-trace    .item()/float()/np.asarray() on traced values
TPL002 async-aliasing        jnp.asarray over mutable numpy buffers
TPL003 op-registry           dup @op names, grad-spec coverage, raw mutation
TPL004 recompile-hazard      time()/np.random/closure scalars under jit
TPL005 collective-safety     lax.p* axis names unbound by any shard_map
TPL006 flag-hygiene          define_flag() names that are never read
TPL007 pallas-autotune-bypass pallas_call sites no tuned() entry reaches
TPL008 gather-sharding-constraint  traced gathers never pinned by a constraint
TPL009 fusion-bypass         model code hand-wiring ops/pallas/fused_* calls
TPL010 metrics-hygiene       stats keys written/declared out of schema lockstep

The analyses are deliberately first-order (per-function taint, per-file
axis sets, project-wide name sets) — precise enough to catch the shipped
bug classes, simple enough that a false positive costs one suppression
comment with a rationale, which doubles as documentation.
"""

from __future__ import annotations

import ast

from .core import Checker, call_name, dotted_name, names_in, str_constants

__all__ = ["ALL_CHECKERS"]


# -- shared helpers ----------------------------------------------------------

_JIT_DECORATORS = {"jit", "pjit", "to_static", "shard_map"}


def _decorator_kind(dec: ast.AST) -> str | None:
    """'op' for @op(...) registrations, 'jit' for jit/to_static-family."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    dotted = dotted_name(target)
    tail = dotted.rsplit(".", 1)[-1] if dotted else ""
    if tail == "op" and dotted in ("op", "dispatch.op"):
        return "op"
    if tail in _JIT_DECORATORS:
        return "jit"
    # functools.partial(jax.jit, static_argnums=...) used as a decorator
    if isinstance(dec, ast.Call) and tail == "partial" and dec.args:
        inner = dotted_name(dec.args[0]).rsplit(".", 1)[-1]
        if inner in _JIT_DECORATORS:
            return "jit"
    return None


def _trace_kind(fn: ast.FunctionDef) -> str | None:
    for dec in fn.decorator_list:
        kind = _decorator_kind(dec)
        if kind:
            return kind
    return None


_SCALAR_ANNOTATIONS = {"bool", "int", "float", "str"}


def _param_names(fn: ast.FunctionDef) -> set[str]:
    """Parameters that may carry traced arrays. Parameters annotated as
    python scalars (``approximate: bool = False``) are static config —
    concretizing them is fine."""
    a = fn.args
    names = set()
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id in _SCALAR_ANNOTATIONS:
            continue
        names.add(p.arg)
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _propagate_taint(fn: ast.AST, seeds: set[str]) -> set[str]:
    """Fixpoint over assignments: a name is tainted if its RHS mentions a
    tainted name. First-order and flow-insensitive on purpose."""
    tainted = set(seeds)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [node.target], node.value
            else:
                continue
            if value is None or not (names_in(value) & tainted):
                continue
            if _is_shape_query(value):
                continue  # n = x.shape[0] is static under tracing
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id not in tainted:
                        tainted.add(n.id)
                        changed = True
    return tainted


def _iter_scope(node: ast.AST):
    """Walk a scope's statements without entering nested function/class
    scopes (those are analyzed as their own scopes)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield child
        yield from _iter_scope(child)


def _is_shape_query(node: ast.AST) -> bool:
    """True if the expression concretizes static metadata (shape/ndim/
    dtype, len()) rather than array *values* — safe under tracing."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim",
                                                       "dtype"):
            return True
        if isinstance(n, ast.Call) and call_name(n) == "len":
            return True
    return False


_NP_ROOTS = ("np.", "numpy.")


def _np_rooted(name: str) -> bool:
    return name.startswith(_NP_ROOTS)


# -- TPL001: host sync inside trace regions ----------------------------------

class HostSyncInTrace(Checker):
    """`.item()` / `float(t)` / `np.asarray(t)` reachable from an `@op`
    lowering or a jit/to_static capture region forces a device→host sync
    and graph-breaks whole-step capture (the `jit/capture.py` bug class)."""

    rule = "TPL001"
    name = "host-sync-in-trace"
    description = ("host-synchronizing conversion of a traced value inside "
                   "an @op lowering or jit/to_static region")

    SYNC_METHODS = {"item", "numpy", "tolist"}
    NP_CONVERTERS = {"np.asarray", "np.array", "np.ascontiguousarray",
                     "numpy.asarray", "numpy.array"}
    CONCRETIZERS = {"float", "bool"}

    def visit_FunctionDef(self, node: ast.FunctionDef):
        kind = _trace_kind(node)
        if kind:
            self._scan(node, kind)
        else:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _scan(self, fn: ast.FunctionDef, kind: str):
        where = ("@op lowering" if kind == "op"
                 else "jit/to_static-traced function")
        tainted = _propagate_taint(fn, _param_names(fn))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # x.item() / x.numpy() / x.tolist(): a sync on anything
            # array-like; inside a trace region there is no safe variant
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.SYNC_METHODS
                    and not node.args):
                self.report(node, f".{node.func.attr}() in {where} "
                                  f"'{fn.name}' forces a device->host sync "
                                  "and breaks program capture")
                continue
            cname = call_name(node)
            if (cname in self.NP_CONVERTERS and node.args
                    and names_in(node.args[0]) & tainted):
                self.report(node, f"{cname}() materializes a traced value "
                                  f"on host in {where} '{fn.name}'")
            elif (cname in self.CONCRETIZERS and len(node.args) == 1
                    and names_in(node.args[0]) & tainted
                    and not _is_shape_query(node.args[0])):
                self.report(node, f"{cname}() concretizes a traced value in "
                                  f"{where} '{fn.name}' (host sync / "
                                  "ConcretizationError under capture)")


# -- TPL002: numpy buffers aliased into async dispatch -----------------------

class AsyncAliasing(Checker):
    """`jnp.asarray` over a live numpy buffer can be zero-copy: if the
    buffer is later mutated while the dispatched program is still in
    flight, the program reads torn data (the `tests/test_serving.py` bug
    class). Requires a defensive copy (`jnp.array`) or a rationale."""

    rule = "TPL002"
    name = "async-aliasing"
    description = ("jnp.asarray over a mutable numpy buffer may alias "
                   "zero-copy into an async in-flight program")

    ASARRAY = {"jnp.asarray", "jax.numpy.asarray"}
    # Under these paths every direct buffer handoff is flagged: programs
    # are dispatched asynchronously, so aliasing is live by construction.
    STRICT_PATHS = ("paddle_tpu/inference/", "paddle_tpu/core/dispatch")
    MUTATORS = {"fill", "sort", "put", "resize", "partition", "setflags"}

    def check(self, ctx):
        self.ctx = ctx
        # attributes that hold numpy state anywhere in the file
        # (self.table = np.zeros(...)): handing one to jnp.asarray is the
        # exact serving-quantum aliasing pattern
        self._np_attrs = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and _np_rooted(call_name(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        self._np_attrs.add(t.attr)
                    elif isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Attribute):
                        self._np_attrs.add(t.value.attr)
        scopes = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        for scope in scopes:
            self._scan_scope(scope)
        self.ctx = None

    @staticmethod
    def _alias_chain(expr: ast.AST):
        """Peel views (subscript/attribute) off an expression.  Returns
        (root_name | None, attrs_along_chain).  A Call anywhere on the
        spine means the argument is a *fresh* object (e.g.
        ``rng.uniform(...)``, ``x.astype(...)``) that nobody else can
        mutate — not an aliasing hazard."""
        attrs = []
        while True:
            if isinstance(expr, ast.Subscript):
                expr = expr.value
            elif isinstance(expr, ast.Attribute):
                attrs.append(expr.attr)
                expr = expr.value
            elif isinstance(expr, ast.Name):
                return expr.id, attrs
            else:
                return None, attrs

    def _scan_scope(self, scope: ast.AST):
        # names bound to numpy buffers in THIS scope
        np_locals: set[str] = set()
        for node in _iter_scope(scope):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                if _np_rooted(call_name(node.value)):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            np_locals.add(t.id)
        strict = any(p in self.ctx.path for p in self.STRICT_PATHS)
        for node in _iter_scope(scope):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in self.ASARRAY and node.args):
                continue
            root, attrs = self._alias_chain(node.args[0])
            if root is None:
                continue
            if root in np_locals:
                what = root
                held = False
            elif set(attrs) & self._np_attrs:
                what = ".".join([root] + list(reversed(attrs)))
                held = True  # attribute-held: outlives the call by design
            else:
                continue
            # Outside the async dispatch paths, a local buffer that is
            # never written after the handoff cannot produce torn reads —
            # only flag buffers that stay live and mutable.
            if not strict and not held and not self._mutated_after(
                    scope, root, node.lineno):
                continue
            self.report(node, f"jnp.asarray over live numpy buffer "
                              f"'{what}' may alias zero-copy into an "
                              "async dispatched program; use jnp.array "
                              "(copies) or justify with a suppression")

    @staticmethod
    def _mutated_after(scope: ast.AST, name: str, line: int) -> bool:
        chain = AsyncAliasing._alias_chain
        for node in _iter_scope(scope):
            if getattr(node, "lineno", 0) <= line:
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    r, _ = chain(t)
                    if r == name and not isinstance(t, ast.Name):
                        return True  # buf[...] = / buf.x = after handoff
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) \
                    and node.func.attr in AsyncAliasing.MUTATORS:
                r, _ = chain(node.func.value)
                if r == name:
                    return True
        return False


# -- TPL003: op-registry consistency -----------------------------------------

class OpRegistryConsistency(Checker):
    """Three invariants of the `@op` funnel (`core/dispatch.py`):
    no duplicate names, no raw `OP_REGISTRY` mutation outside the
    decorator, and every `differentiable=True` registration accounted for
    by the machine-checked grad inventory (spec / NONDIFF_NATURE /
    ALLOWLIST / STE_OPS in tests/test_grad_coverage.py)."""

    rule = "TPL003"
    name = "op-registry"
    description = ("duplicate @op names, grad-coverage gaps, or registry "
                   "mutation outside the decorator")

    REGISTRY_NAMES = {"OP_REGISTRY"}
    MUTATORS = {"pop", "update", "clear", "setdefault", "popitem"}
    ACCOUNTING_SETS = {"NONDIFF_NATURE", "ALLOWLIST", "STE_OPS"}
    GRAD_FILE_HINT = "test_grad_coverage"
    DISPATCH_HOME = "core/dispatch.py"

    def __init__(self):
        super().__init__()
        # name -> list of (path, line)
        self.registrations: dict[str, list] = {}
        # (name, path, line) for differentiable registrations
        self.differentiable: list[tuple] = []
        self.accounted: set[str] = set()
        self.grad_file_seen = False
        self._consumed: set[int] = set()

    def check(self, ctx):
        self.ctx = ctx
        if self.GRAD_FILE_HINT in ctx.path.rsplit("/", 1)[-1]:
            self.grad_file_seen = True
            self._harvest_accounting(ctx.tree)
        self._consumed = set()
        self.visit(ctx.tree)
        self.ctx = None

    # -- registrations -------------------------------------------------------

    def _record(self, name: str, node: ast.AST, diff: bool):
        self.registrations.setdefault(name, []).append(
            (self.ctx.path, node.lineno, node))
        if diff:
            self.differentiable.append((name, self.ctx.path, node.lineno,
                                        node))

    @staticmethod
    def _op_call_parts(call: ast.Call):
        """(name_literal | None, differentiable) for an op(...) call."""
        name = None
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            name = call.args[0].value
        diff = True
        for kw in call.keywords:
            if kw.arg == "differentiable" and isinstance(kw.value,
                                                         ast.Constant):
                diff = bool(kw.value.value)
        return name, diff

    def visit_FunctionDef(self, node: ast.FunctionDef):
        for dec in node.decorator_list:
            if _decorator_kind(dec) != "op":
                continue
            if isinstance(dec, ast.Call):
                self._consumed.add(id(dec))
                name, diff = self._op_call_parts(dec)
                if name is None and dec.args:
                    continue  # dynamic name (variable/f-string): out of
                    # static reach — the runtime inventory still covers it
                self._record(name or node.name, dec, diff)
            else:  # bare @op
                self._record(node.name, dec, True)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        if _decorator_kind(node) == "op" and id(node) not in self._consumed:
            name, diff = self._op_call_parts(node)
            if name is not None:  # dynamic names (loop registrations) are
                self._record(name, node, diff)  # out of static reach
        self._check_mutation(node)
        self.generic_visit(node)

    # -- raw registry mutation -----------------------------------------------

    def _in_dispatch(self) -> bool:
        return self.ctx.path.endswith(self.DISPATCH_HOME)

    def _registry_subscript(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Subscript)
                and dotted_name(node.value).rsplit(".", 1)[-1]
                in self.REGISTRY_NAMES)

    def _check_mutation(self, call: ast.Call):
        if self._in_dispatch():
            return
        f = call.func
        if (isinstance(f, ast.Attribute) and f.attr in self.MUTATORS
                and dotted_name(f.value).rsplit(".", 1)[-1]
                in self.REGISTRY_NAMES):
            self.report(call, f"OP_REGISTRY.{f.attr}() outside the @op "
                              "decorator funnel (core/dispatch.py); register "
                              "through @op so AMP/grad/consistency metadata "
                              "stays attached")

    def visit_Assign(self, node: ast.Assign):
        if not self._in_dispatch():
            for t in node.targets:
                if self._registry_subscript(t):
                    self.report(node, "direct OP_REGISTRY[...] assignment "
                                      "outside the @op decorator funnel "
                                      "(core/dispatch.py)")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        if not self._in_dispatch():
            for t in node.targets:
                if self._registry_subscript(t):
                    self.report(node, "del OP_REGISTRY[...] outside "
                                      "core/dispatch.py")
        self.generic_visit(node)

    # -- grad accounting ------------------------------------------------------

    def _harvest_accounting(self, tree: ast.AST):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                cname = call_name(node)
                if cname == "spec" and node.args and isinstance(
                        node.args[0], ast.Constant):
                    self.accounted.add(node.args[0].value)
                elif cname == "unary" and node.args and isinstance(
                        node.args[0], ast.Constant):
                    self.accounted.update(str(node.args[0].value).split())
            elif isinstance(node, ast.For):
                # `for n in "sum mean ...".split(): spec(n, ...)` and
                # `for name, layer in [("relu", ...)]: spec(name, ...)`
                body_specs = any(
                    isinstance(n, ast.Call) and call_name(n) in ("spec",
                                                                 "unary")
                    for n in ast.walk(node))
                if body_specs:
                    for s in str_constants(node.iter):
                        self.accounted.update(s.split())
            elif isinstance(node, ast.Assign):
                targets = {t.id for t in node.targets
                           if isinstance(t, ast.Name)}
                if targets & self.ACCOUNTING_SETS:
                    if isinstance(node.value, ast.Dict):
                        for k in node.value.keys:
                            if isinstance(k, ast.Constant) and isinstance(
                                    k.value, str):
                                self.accounted.add(k.value)
                    else:
                        for s in str_constants(node.value):
                            self.accounted.update(s.split())

    def finalize(self):
        for name, sites in sorted(self.registrations.items()):
            if len(sites) > 1:
                first = f"{sites[0][0]}:{sites[0][1]}"
                for path, line, node in sites[1:]:
                    self.report(node, f"duplicate @op registration '{name}' "
                                      f"(first registered at {first}); "
                                      "later registration silently wins",
                                path=path, line=line)
        if self.grad_file_seen:
            for name, path, line, node in self.differentiable:
                if name not in self.accounted:
                    self.report(node, f"op '{name}' is registered "
                                      "differentiable=True but has no grad "
                                      "spec, NONDIFF_NATURE/ALLOWLIST/"
                                      "STE_OPS entry in the grad-coverage "
                                      "inventory", path=path, line=line)


# -- TPL004: recompile hazards under jit/to_static ---------------------------

class RecompileHazard(Checker):
    """`time.time()` / `np.random.*` / loop-variable closure captures
    inside jit/to_static regions either retrace every step or — worse —
    bake a stale constant into the compiled program."""

    rule = "TPL004"
    name = "recompile-hazard"
    description = ("impure host calls or mutable closure captures inside a "
                   "jit/to_static region")

    HAZARD_CALLS = {
        "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
        "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    }
    HAZARD_PREFIXES = ("np.random.", "numpy.random.", "random.")

    def _is_hazard(self, cname: str) -> bool:
        return cname in self.HAZARD_CALLS or (
            cname.startswith(self.HAZARD_PREFIXES)
            and not cname.startswith(("random.Random",)))

    def visit_FunctionDef(self, node: ast.FunctionDef):
        if _trace_kind(node):
            self._scan_trace_fn(node, outer_hazards={}, loop_vars=set())
        else:
            self._scan_host_fn(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _scan_host_fn(self, fn: ast.FunctionDef):
        """Record hazard-derived locals and loop variables, then inspect
        nested trace-context functions for closure captures of them."""
        hazards: dict[str, int] = {}
        loops: list[tuple[ast.For, set[str]]] = []
        for node in _iter_scope(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                if self._is_hazard(call_name(node.value)):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            hazards[t.id] = node.lineno
        for node in ast.walk(fn):
            if isinstance(node, ast.For):
                loops.append((node, names_in(node.target)))
        for node in ast.walk(fn):
            if node is not fn and isinstance(node, (ast.FunctionDef,
                                                    ast.AsyncFunctionDef)):
                if _trace_kind(node):
                    # a traced fn defined INSIDE the loop body is fresh
                    # per iteration — capturing that iteration's variable
                    # is the normal pattern, not a staleness hazard
                    loop_vars = set()
                    for for_node, targets in loops:
                        if not any(n is node for n in ast.walk(for_node)):
                            loop_vars |= targets
                    self._scan_trace_fn(node, hazards, loop_vars)

    def _scan_trace_fn(self, fn: ast.FunctionDef,
                       outer_hazards: dict, loop_vars: set):
        # everything bound inside the traced fn itself is local, including
        # its own loop targets and comprehension variables
        local = _param_names(fn) | {
            n.id for n in ast.walk(fn)
            if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                              ast.Del))}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                cname = call_name(node)
                if self._is_hazard(cname):
                    self.report(node, f"{cname}() inside jit/to_static "
                                      f"region '{fn.name}' is evaluated at "
                                      "trace time and baked in as a "
                                      "constant (recompile/staleness "
                                      "hazard)")
            elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                           ast.Load):
                if node.id in local:
                    continue
                if node.id in outer_hazards:
                    self.report(node, f"closure capture of '{node.id}' "
                                      "(derived from an impure host call at "
                                      f"line {outer_hazards[node.id]}) in "
                                      f"traced function '{fn.name}': the "
                                      "value is frozen at trace time")
                elif node.id in loop_vars:
                    self.report(node, f"closure capture of loop variable "
                                      f"'{node.id}' in traced function "
                                      f"'{fn.name}': jit caches on "
                                      "signature, not closure — iterations "
                                      "after the first reuse a stale "
                                      "constant")


# -- TPL005: collective axis safety ------------------------------------------

class CollectiveSafety(Checker):
    """A `lax.p*` collective naming a mesh axis that no `shard_map` /
    `Mesh` / `PartitionSpec` in the file binds fails at trace time deep
    inside XLA with an unbound-axis error — or, if the literal drifts
    from the binding site, silently reduces over the wrong axis."""

    rule = "TPL005"
    name = "collective-safety"
    description = ("lax collective referencing a mesh axis not bound by "
                   "any shard_map/Mesh/spec in the file")

    COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "ppermute",
                   "all_gather", "psum_scatter", "all_to_all",
                   "axis_index", "pbroadcast", "pshuffle"}
    BINDERS = {"shard_map", "Mesh", "make_mesh", "P", "PartitionSpec",
               "pmap", "xmap"}
    BINDER_KWARGS = {"axis_names", "axis_name", "in_specs", "out_specs"}

    def check(self, ctx):
        self.ctx = ctx
        bound: set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = call_name(node).rsplit(".", 1)[-1]
            if tail in self.BINDERS:
                bound |= str_constants(node)
            else:
                for kw in node.keywords:
                    if kw.arg in self.BINDER_KWARGS:
                        bound |= str_constants(kw.value)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            root, _, tail = cname.rpartition(".")
            if tail not in self.COLLECTIVES or root not in ("lax",
                                                            "jax.lax"):
                continue
            axis_pos = 0 if tail == "axis_index" else 1
            axis_arg = None
            if len(node.args) > axis_pos:
                axis_arg = node.args[axis_pos]
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis"):
                    axis_arg = kw.value
            if axis_arg is None:
                continue
            for ax in sorted(str_constants(axis_arg)):
                if ax not in bound:
                    self.report(node, f"collective lax.{tail}('{ax}') "
                                      "references mesh axis "
                                      f"'{ax}' not bound by any shard_map/"
                                      "Mesh/PartitionSpec in this file")


# -- TPL006: flag hygiene ----------------------------------------------------

class FlagHygiene(Checker):
    """A `define_flag()` whose name is never read anywhere in the tree is
    dead configuration surface: it silently accepts FLAGS_* env overrides
    and set_flags() writes that change nothing.

    The same hygiene covers the two env-var config surfaces:

    - an ``os.environ`` read of ``"FLAGS_<name>"`` counts as a read of
      flag ``<name>`` (the env-override path IS a consumer);
    - ``PT_CHAOS_*`` knobs (the chaos-harness env surface,
      paddle_tpu/testing/chaos.py) that are *set* somewhere
      (``os.environ["PT_CHAOS_X"] = ...`` / ``monkeypatch.setenv``) but
      never read by any ``os.environ`` access are reported — an armed
      fault knob nothing consumes is a test that silently stopped
      injecting.
    """

    rule = "TPL006"
    name = "flag-hygiene"
    severity = "warning"
    description = "defined runtime flag / chaos env knob that nothing reads"

    _CHAOS_PREFIX = "PT_CHAOS_"
    _FLAGS_PREFIX = "FLAGS_"
    _ENV_READ_TAILS = {"environ.get", "getenv", "environ.pop",
                       "environ.setdefault"}

    def __init__(self):
        super().__init__()
        self.defines: dict[str, tuple] = {}   # name -> (path, line, node)
        self.reads: set[str] = set()
        self.env_defines: dict[str, tuple] = {}   # PT_CHAOS_* setters
        self.env_reads: set[str] = set()

    def _note_env_read(self, name: str):
        if name.startswith(self._FLAGS_PREFIX):
            self.reads.add(name[len(self._FLAGS_PREFIX):])
        elif name.startswith(self._CHAOS_PREFIX):
            self.env_reads.add(name)

    def visit_Call(self, node: ast.Call):
        cname = call_name(node)
        tail = cname.rsplit(".", 1)[-1]
        first = (node.args[0].value
                 if node.args and isinstance(node.args[0], ast.Constant)
                 and isinstance(node.args[0].value, str) else None)
        if tail == "define_flag" and first is not None:
            self.defines.setdefault(first, (self.ctx.path, node.lineno,
                                            node))
        elif tail == "define" and "FLAGS" in cname.upper() \
                and first is not None:
            self.defines.setdefault(first, (self.ctx.path, node.lineno,
                                            node))
        elif tail in ("get", "has") and first is not None:
            # any .get("name")/.has("name") counts as a read — that also
            # matches dict.get, which is deliberately conservative (a flag
            # is only reported when NOTHING in the tree could read it)
            self.reads.add(first)
        elif tail == "get_flags" and node.args:
            self.reads.update(str_constants(node.args[0]))
        if first is not None:
            if any(cname.endswith(t) for t in self._ENV_READ_TAILS):
                self._note_env_read(first)
            elif tail == "setenv" and first.startswith(self._CHAOS_PREFIX):
                self.env_defines.setdefault(first, (self.ctx.path,
                                                    node.lineno, node))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        key = (node.slice.value if isinstance(node.slice, ast.Constant)
               and isinstance(node.slice.value, str) else None)
        if key is not None and dotted_name(node.value).endswith("environ"):
            if isinstance(node.ctx, ast.Store) and \
                    key.startswith(self._CHAOS_PREFIX):
                self.env_defines.setdefault(key, (self.ctx.path,
                                                  node.lineno, node))
            else:
                self._note_env_read(key)
        self.generic_visit(node)

    def finalize(self):
        for name, (path, line, node) in sorted(self.defines.items()):
            if name not in self.reads:
                self.report(node, f"flag '{name}' is defined but never "
                                  "read by any code in the analyzed tree "
                                  "(dead configuration surface)",
                            path=path, line=line)
        for name, (path, line, node) in sorted(self.env_defines.items()):
            if name not in self.env_reads:
                self.report(node, f"chaos env knob '{name}' is set but "
                                  "never read by any os.environ access in "
                                  "the analyzed tree (fault injection that "
                                  "cannot fire)", path=path, line=line)


# -- TPL007: pallas_call sites that bypass the autotune registry -------------

class PallasAutotuneBypass(Checker):
    """A ``pl.pallas_call`` whose block/grid configuration is hardwired
    and never consulted against the persistent autotune registry
    (paddle_tpu/ops/pallas/autotune.py) freezes a hand-tuned shape choice
    into the kernel: the sweep can never revisit it on a new device kind
    or shape bucket, which is exactly how the pre-registry kernels ended
    up with one-device block constants.

    Module-local reachability analysis (first-order, like TPL005/006):
    a function is "tuned" if its body calls anything whose dotted tail
    contains ``tuned`` (``autotune.tuned``, ``_tuned_blocks``, ...) or
    references ``GLOBAL_AUTOTUNE``; tuned-ness propagates along the
    module's reference graph (a tuned entry point passes its swept
    parameters into the wrappers and kernels it references, including
    ``custom_vjp``/``defvjp`` wiring at module scope). A ``pallas_call``
    in a function no tuned entry reaches — or at module scope — is
    reported. Deliberate fixed-geometry kernels (e.g. paged decode
    wrappers whose blocks ARE the page size) suppress with a rationale.
    """

    rule = "TPL007"
    name = "pallas-autotune-bypass"
    severity = "warning"
    description = "pallas_call block/grid config bypasses the autotune registry"

    def check(self, ctx):
        self.ctx = ctx
        funcs: dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, node)

        def is_tuned(body: ast.AST) -> bool:
            for n in ast.walk(body):
                if isinstance(n, ast.Call) and \
                        "tuned" in call_name(n).rsplit(".", 1)[-1]:
                    return True
                if isinstance(n, ast.Name) and n.id == "GLOBAL_AUTOTUNE":
                    return True
                if isinstance(n, ast.Attribute) and \
                        n.attr == "GLOBAL_AUTOTUNE":
                    return True
            return False

        refs: dict[str, set[str]] = {}
        tuned: set[str] = set()
        sites: dict[str, list[ast.Call]] = {}
        module_sites: list[ast.Call] = []

        for name, fn in funcs.items():
            refs[name] = {n.id for n in ast.walk(fn)
                          if isinstance(n, ast.Name) and n.id != name
                          and n.id in funcs} | \
                         {n.attr for n in ast.walk(fn)
                          if isinstance(n, ast.Attribute)
                          and n.attr in funcs}
            if is_tuned(fn):
                tuned.add(name)

        def owner(call: ast.Call) -> str | None:
            best = None
            for name, fn in funcs.items():
                if (fn.lineno <= call.lineno <= (fn.end_lineno or fn.lineno)
                        and (best is None
                             or fn.lineno > funcs[best].lineno)):
                    best = name
            return best

        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call) and \
                    call_name(n).rsplit(".", 1)[-1] == "pallas_call":
                own = owner(n)
                if own is None:
                    module_sites.append(n)
                else:
                    sites.setdefault(own, []).append(n)
            # module-scope `X.defvjp(fwd, bwd)` wires fwd/bwd into X's
            # reference graph even though no function body mentions them
            if isinstance(n, ast.Call) and \
                    call_name(n).rsplit(".", 1)[-1] == "defvjp":
                root = call_name(n).rsplit(".", 1)[0]
                if root in funcs:
                    refs.setdefault(root, set()).update(
                        a.id for a in n.args
                        if isinstance(a, ast.Name) and a.id in funcs)

        changed = True
        while changed:
            changed = False
            for name in list(tuned):
                for callee in refs.get(name, ()):
                    if callee not in tuned:
                        tuned.add(callee)
                        changed = True

        for name, calls in sorted(sites.items()):
            if name in tuned:
                continue
            for call in calls:
                self.report(call, f"pallas_call in '{name}' with hardcoded "
                                  "block/grid config: no autotune-consulting "
                                  "entry point reaches it — route the block "
                                  "parameters through ops/pallas/autotune."
                                  "tuned() or suppress with a rationale")
        for call in module_sites:
            self.report(call, "module-scope pallas_call with hardcoded "
                              "block/grid config bypasses the autotune "
                              "registry")
        self.ctx = None


# -- TPL008: unconstrained gathers on sharded operands ------------------------

class GatherShardingConstraint(Checker):
    """An embedding-style gather (``table[ids]`` / ``jnp.take``) over
    traced operands in a file that manipulates shardings, whose result is
    never pinned by a sharding constraint. GSPMD picks the gather's output
    layout by cost model, so a downstream layout (the ZeRO-sharded
    optimizer moments in MULTICHIP_r05) back-propagates onto the gather
    and the resulting reshard is an involuntary full rematerialization of
    ``f32[B,T,H]``. The fix shipped in models/gpt.py: pin the gather
    through a ``*constraint*`` call the moment the value exists — either
    wrapping the gather directly (``constraint(params["wte"][tokens])``)
    or rebinding its target before further use (``emb = params["wte"]
    [tokens]`` then ``emb = emb_constraint(emb)``). Both shapes clear the
    rule; gathers whose result escapes unpinned are reported.

    First-order like the rest of the suite: the rule only looks at files
    that reference sharding machinery at all, treats function parameters
    (and anything assigned from them) as potentially mesh-sharded, and
    skips static indexing (constants, slices, shape queries)."""

    rule = "TPL008"
    name = "gather-sharding-constraint"
    severity = "warning"
    description = ("traced gather (x[ids]/jnp.take) in a sharding-aware "
                   "file whose result is never pinned by a sharding "
                   "constraint")

    SHARDING_MARKS = ("PartitionSpec", "NamedSharding", "shard_map",
                      "with_sharding_constraint", "get_abstract_mesh")
    TAKE_CALLS = {"jnp.take", "jax.numpy.take"}

    def check(self, ctx):
        if not any(m in ctx.source for m in self.SHARDING_MARKS):
            return  # file never touches shardings: gathers are GSPMD-free
        self.ctx = ctx
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan(node)
        self.ctx = None

    def _is_gather(self, node: ast.AST, tainted: set) -> bool:
        if isinstance(node, ast.Subscript) and isinstance(node.ctx,
                                                          ast.Load):
            sl = node.slice
            # static indexing — constants, slices, tuple/advanced mixes,
            # shape-derived scalars — never produces a sharded gather
            if isinstance(sl, (ast.Constant, ast.Slice, ast.Tuple)):
                return False
            if _is_shape_query(sl):
                return False
            # embedding-table shape: params["wte"][tokens] — a string-
            # keyed entry of a traced pytree indexed by a traced array.
            # Bare ``seq[i]`` subscripts are host-side container lookups
            # far more often than array gathers; out of static reach on
            # purpose (jnp.take covers the explicit-gather spelling).
            base = node.value
            if not (isinstance(base, ast.Subscript)
                    and isinstance(base.slice, ast.Constant)
                    and isinstance(base.slice.value, str)):
                return False
            return bool(names_in(sl) & tainted) \
                and bool(names_in(base.value) & tainted)
        if isinstance(node, ast.Call) and call_name(node) in \
                self.TAKE_CALLS and node.args:
            return bool(names_in(node.args[0]) & tainted)
        return False

    @staticmethod
    def _is_constraint_call(node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and \
            "constraint" in call_name(node).rsplit(".", 1)[-1]

    def _scan(self, fn: ast.FunctionDef):
        tainted = _propagate_taint(fn, _param_names(fn))
        # every node that sits inside a *constraint* call's arguments is
        # pinned at birth (constraint(params["wte"][tokens]))
        pinned: set[int] = set()
        for node in _iter_scope(fn):
            if self._is_constraint_call(node):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        pinned.add(id(sub))
        # names rebound through a constraint call (emb = emb_constraint(
        # emb)), by line — clears gathers assigned to them earlier
        rebinds: dict[str, list[int]] = {}
        for node in _iter_scope(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and self._is_constraint_call(node.value) \
                    and node.targets[0].id in names_in(node.value):
                rebinds.setdefault(node.targets[0].id,
                                   []).append(node.lineno)
        for node in _iter_scope(fn):
            if not isinstance(node, ast.Assign):
                continue
            target = (node.targets[0]
                      if len(node.targets) == 1
                      and isinstance(node.targets[0], ast.Name) else None)
            if target is None:
                continue
            for sub in ast.walk(node.value):
                if id(sub) in pinned or not self._is_gather(sub, tainted):
                    continue
                if any(ln > node.lineno
                       for ln in rebinds.get(target.id, ())):
                    pinned.add(id(sub))  # rebound through a constraint
        for node in _iter_scope(fn):
            for_report = None
            if isinstance(node, (ast.Subscript, ast.Call)) \
                    and id(node) not in pinned \
                    and self._is_gather(node, tainted):
                for_report = node
            if for_report is not None:
                if isinstance(for_report, ast.Call):
                    what = "jnp.take"
                else:  # _is_gather guarantees a str-keyed Subscript base
                    b = for_report.value
                    what = f"{dotted_name(b.value)}[{b.slice.value!r}][...]"
                self.report(for_report,
                            f"{what} gathers a traced index over a "
                            "potentially mesh-sharded operand in "
                            f"'{fn.name}' without a sharding constraint: "
                            "GSPMD chooses the output layout by cost "
                            "model and may reshard with an involuntary "
                            "full rematerialization — pin it with "
                            "with_sharding_constraint (or an injected "
                            "*_constraint hook) the moment it exists")


# -- TPL009: hand-wired fusion bypass ----------------------------------------

class HandWiredFusionBypass(Checker):
    """Model/runtime code that imports a Pallas megakernel from
    ``ops/pallas/fused_*`` and calls it directly has hand-wired a fusion
    the jaxpr-level pass (paddle_tpu/compiler/) discovers on its own.
    Hand-wired sites sit outside the per-program autotune record and the
    catalog's parity pins, and they keep firing even when
    ``use_auto_fusion=0`` asks for the exact unfused baseline — the bug
    class PR 6 shipped and ISSUE 15 retired.  Route the call through
    ``compiler.fused_call``/``auto_fuse`` (or keep the op-by-op
    composition and let the pass rewrite it).

    Exempt: the kernel homes themselves (``paddle_tpu/ops/``), the
    compiler that is allowed to build the calls (``paddle_tpu/compiler/``),
    and kernel parity tests (``test_*.py`` — pinning the kernel against
    its composition REQUIRES calling it directly).  ``*_supported()``
    capability probes only gate, never compute, and are not flagged.
    """

    rule = "TPL009"
    name = "fusion-bypass"
    severity = "warning"
    description = ("direct ops/pallas/fused_* kernel call outside the "
                   "fusion pass — hand-wired fusion the compiler should "
                   "discover")

    _FUSED_HOME = "ops.pallas.fused"
    _EXEMPT_DIRS = ("paddle_tpu/ops/", "paddle_tpu/compiler/")

    def check(self, ctx):
        path = ctx.path.replace("\\", "/")
        if any(d in path for d in self._EXEMPT_DIRS):
            return
        if path.rsplit("/", 1)[-1].startswith("test_"):
            return
        self.ctx = ctx
        direct: dict[str, ast.AST] = {}   # imported kernel name -> import
        aliases: dict[str, ast.AST] = {}  # module alias -> import
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    bound = a.asname or a.name
                    if self._FUSED_HOME in mod:
                        if not a.name.endswith("_supported"):
                            direct[bound] = node
                    elif (mod.endswith("ops.pallas")
                          or mod.endswith("pallas")) \
                            and a.name.startswith("fused_"):
                        aliases[bound] = node
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if self._FUSED_HOME in a.name:
                        aliases[a.asname or a.name] = node
        if not direct and not aliases:
            self.ctx = None
            return
        called: set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            tail = cname.rsplit(".", 1)[-1]
            if cname in direct:
                called.add(cname)
                self.report(node, f"direct call of Pallas megakernel "
                                  f"'{cname}' hand-wires a fusion the "
                                  "compiler pass discovers from the jaxpr; "
                                  "route through compiler.fused_call/"
                                  "auto_fuse or suppress with a rationale")
            elif "." in cname:
                root = cname.rsplit(".", 1)[0]
                if root in aliases and tail.startswith("fused_") \
                        and not tail.endswith("_supported"):
                    called.add(root)
                    self.report(node, f"direct call of Pallas megakernel "
                                      f"'{cname}' hand-wires a fusion the "
                                      "compiler pass discovers from the "
                                      "jaxpr; route through compiler."
                                      "fused_call/auto_fuse or suppress "
                                      "with a rationale")
        for bound, node in {**direct, **aliases}.items():
            if bound not in called:
                self.report(node, f"import of Pallas megakernel surface "
                                  f"'{bound}' from ops/pallas/fused_* in "
                                  "non-kernel code: the fusion pass "
                                  "(paddle_tpu/compiler/) should be the "
                                  "only caller")
        self.ctx = None


# -- TPL010: metrics hygiene -------------------------------------------------

class MetricsHygiene(Checker):
    """Runtime ``stats`` counters and their declared schema drift apart
    silently: a key written in serving/fleet code but absent from every
    ``*_STATS_SCHEMA`` dict (paddle_tpu/obs/metrics.py) never reaches the
    metrics registry, the Prometheus export or the flight recorder; a
    key declared in a schema but written nowhere is a dashboard series
    that flatlines at zero forever. Both directions are reported.

    Key extraction from ``x.stats[...]`` store sites is deliberately
    conservative (first-order, like TPL006): a string constant is that
    key; a conditional expression contributes the union of both arms;
    anything else (computed keys, loop variables) is dynamic and
    skipped. Dynamic writes instead earn their keys *mention credit* —
    any string literal outside a schema dict that equals a declared key
    (e.g. the ``self._drop(req, "n_shed")`` call-site literal) counts as
    a writer, so a declared key is only reported when NOTHING in the
    tree could name it.
    """

    rule = "TPL010"
    name = "metrics-hygiene"
    severity = "warning"
    description = "stats key written but undeclared, or declared but never written"

    _SCHEMA_SUFFIX = "_STATS_SCHEMA"

    def __init__(self):
        super().__init__()
        self.declared: dict[str, tuple] = {}  # key -> (path, line, node)
        self.writes: dict[str, list] = {}     # key -> [(path, line, node)]
        self.mentions: set[str] = set()       # str literals outside schemas

    def _literal_keys(self, expr: ast.AST) -> list[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return [expr.value]
        if isinstance(expr, ast.IfExp):
            # both arms are possible at runtime; the test is irrelevant
            return self._literal_keys(expr.body) + \
                self._literal_keys(expr.orelse)
        return []  # dynamic key — handled by mention credit

    def visit_Assign(self, node: ast.Assign):
        tgt = node.targets[0] if len(node.targets) == 1 else None
        name = dotted_name(tgt) if tgt is not None else ""
        if name.endswith(self._SCHEMA_SUFFIX) and \
                isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    self.declared.setdefault(
                        k.value, (self.ctx.path, k.lineno, k))
            return  # don't descend: a declaration is not mention credit
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if isinstance(node.ctx, ast.Store) and \
                dotted_name(node.value).rsplit(".", 1)[-1] == "stats":
            for key in self._literal_keys(node.slice):
                self.writes.setdefault(key, []).append(
                    (self.ctx.path, node.lineno, node))
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str):
            self.mentions.add(node.value)

    def finalize(self):
        if not self.declared:
            return  # no schema in the analyzed tree — nothing to check
        for key, sites in sorted(self.writes.items()):
            if key not in self.declared:
                path, line, node = sites[0]
                self.report(node, f"stats key '{key}' is written but not "
                                  "declared in any *_STATS_SCHEMA dict — "
                                  "the metrics registry cannot absorb it "
                                  "(declare it, or rename the write)",
                            path=path, line=line)
        for key, (path, line, node) in sorted(self.declared.items()):
            if key not in self.mentions:
                self.report(node, f"stats key '{key}' is declared in a "
                                  "*_STATS_SCHEMA but never written (or "
                                  "even named) by any code in the analyzed "
                                  "tree — a metric series that flatlines "
                                  "at zero", path=path, line=line)


ALL_CHECKERS = [
    HostSyncInTrace,
    AsyncAliasing,
    OpRegistryConsistency,
    RecompileHazard,
    CollectiveSafety,
    FlagHygiene,
    PallasAutotuneBypass,
    GatherShardingConstraint,
    HandWiredFusionBypass,
    MetricsHygiene,
]
