"""tpu-lint: AST-based static analysis for paddle_tpu's bug classes.

Checks trace-safety (host syncs under capture), async aliasing of numpy
buffers, op-registry consistency against the grad-coverage inventory,
recompile hazards, collective axis binding, flag hygiene — plus the
whole-program interprocedural rules (TPL101-TPL103, call-chain taint
over the project import/call graph; tools/lint/interproc.py), the wire
protocol typestate rules (TPL211-TPL213; tools/lint/typestate.py),
abstract op-contract verification (``--contracts``;
tools/lint/contracts.py), static sharding/collective verification
over traced entry-program jaxprs (``--shardcheck``, rules
TPL201-TPL204; tools/lint/shardcheck.py), and static precision &
scale-provenance verification over the same entry set
(``--quantcheck``, rules TPL300-TPL305, plus the
``--quantcheck-regression`` scale-leak gate; tools/lint/quantcheck.py).

    python -m tools.lint paddle_tpu tests [--format=json|sarif]
    python -m tools.lint --contracts --baseline artifacts/op_contracts.json
    python -m tools.lint --shardcheck --baseline artifacts/shardcheck.json
    python -m tools.lint --quantcheck --baseline artifacts/quantcheck.json

See ``tools/lint/checkers.py`` + ``tools/lint/interproc.py`` for the
rule table, ``tools/lint/ARCHITECTURE.md`` for the call-graph/fixpoint
design, and the README section "Static analysis (tpu-lint)" for
suppression syntax and how to add a checker.
"""

from .cli import ALL_CHECKERS, DEFAULT_EXCLUDES, iter_python_files, main, run_lint
from .core import Checker, FileContext, Finding, Suppressions
from .interproc import INTERPROC_CHECKERS, ProjectIndex
from .reporters import render_json, render_sarif, render_text
from .typestate import TYPESTATE_CHECKERS

__all__ = [
    "ALL_CHECKERS",
    "Checker",
    "DEFAULT_EXCLUDES",
    "FileContext",
    "Finding",
    "INTERPROC_CHECKERS",
    "ProjectIndex",
    "Suppressions",
    "TYPESTATE_CHECKERS",
    "iter_python_files",
    "main",
    "render_json",
    "render_sarif",
    "render_text",
    "run_lint",
]
