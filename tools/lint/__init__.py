"""tpu-lint: AST-based static analysis for paddle_tpu's bug classes.

Checks trace-safety (host syncs under capture), async aliasing of numpy
buffers, op-registry consistency against the grad-coverage inventory,
recompile hazards, collective axis binding, and flag hygiene.

    python -m tools.lint paddle_tpu tests [--format=json]

See ``tools/lint/checkers.py`` for the rule table and the README section
"Static analysis (tpu-lint)" for suppression syntax and how to add a
checker.
"""

from .checkers import ALL_CHECKERS
from .cli import DEFAULT_EXCLUDES, iter_python_files, main, run_lint
from .core import Checker, FileContext, Finding, Suppressions
from .reporters import render_json, render_text

__all__ = [
    "ALL_CHECKERS",
    "Checker",
    "DEFAULT_EXCLUDES",
    "FileContext",
    "Finding",
    "Suppressions",
    "iter_python_files",
    "main",
    "render_json",
    "render_text",
    "run_lint",
]
