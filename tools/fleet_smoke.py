"""Fleet smoke gate (ci_check.sh exit 100): a 2-replica FleetRouter on
a tiny config loses one engine mid-decode — every accepted request must
still complete, every victim stream (greedy AND sampled) must be
bit-identical to an uninterrupted solo run, at least one KV page must
have migrated off the dead replica, and the survivor's page ledger must
settle to free + cache_idle only (zero leak, nothing stuck in_flight).

Usage:  JAX_PLATFORMS=cpu python -m tools.fleet_smoke
"""

from __future__ import annotations

import sys

import numpy as np


def main() -> int:
    import jax.numpy as jnp

    from paddle_tpu.inference.fleet import FleetRouter
    from paddle_tpu.inference.serving import Request, ServingEngine
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_hidden=128, max_seq_len=256,
                      dtype=jnp.float32, param_dtype=jnp.float32)
    ekw = dict(max_batch=2, page_size=16, max_seq=128, n_pages=1 + 24,
               prefill_budget=32)
    router = FleetRouter(cfg, n_engines=2, seed=0, engine_kwargs=ekw)
    params = router.replicas[0].engine.params

    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, cfg.vocab_size, size=40).astype(np.int32)
               for _ in range(5)]
    reqs = [Request(rid=i, prompt=p, max_new_tokens=12, arrival=0.0)
            for i, p in enumerate(prompts)]
    # one sampled stream: resume bit-identity must hold through the
    # keyed (seed, position) sampling path too, not just argmax
    reqs[2].temperature, reqs[2].top_p, reqs[2].seed = 0.8, 0.9, 1234

    for r in reqs:
        router.submit(r, now=1e18)

    # step until some replica holds a mid-decode stream, then kill it —
    # the victim must carry emitted tokens so pages actually migrate
    victim_engine = None
    for _ in range(200):
        router.step(now=1e18)
        for rep in router.replicas:
            if any(r is not None and 0 < len(r.out_tokens)
                   < r.max_new_tokens for r in rep.engine.slots):
                victim_engine = rep
                break
        if victim_engine is not None:
            break
    if victim_engine is None:
        print("fleet_smoke: FAIL — no mid-decode stream appeared to "
              "kill", file=sys.stderr)
        return 1
    router.kill_engine(victim_engine.engine.engine_id, now=1e18)

    steps = 0
    while router.step(now=1e18):
        steps += 1
        if steps > 2000:
            print("fleet_smoke: FAIL — fleet did not drain after the "
                  "kill", file=sys.stderr)
            return 1

    bad = [r for r in reqs if r.aborted or r.t_done is None
           or len(r.out_tokens) != r.max_new_tokens]
    if bad:
        print(f"fleet_smoke: FAIL — incomplete/aborted requests "
              f"{[r.rid for r in bad]} after the kill", file=sys.stderr)
        return 1
    if router.stats["migrated_pages"] < 1:
        print("fleet_smoke: FAIL — kill recovered without migrating a "
              "single page", file=sys.stderr)
        return 1

    # bit-identity: every stream equals an uninterrupted solo run on a
    # fresh engine sharing the same params
    for r in reqs:
        solo_eng = ServingEngine(cfg, params=params, seed=0, **ekw)
        solo = Request(rid=100 + r.rid, prompt=r.prompt.copy(),
                       max_new_tokens=r.max_new_tokens,
                       temperature=r.temperature, top_p=r.top_p,
                       seed=r.seed)
        solo_eng.run([solo])
        if solo.out_tokens != r.out_tokens:
            print(f"fleet_smoke: FAIL — rid {r.rid} stream differs "
                  f"from its uninterrupted run: {r.out_tokens} vs "
                  f"{solo.out_tokens}", file=sys.stderr)
            return 1

    # survivor ledgers settle to free + cache_idle only; the dead
    # replica's frozen pool still sums (death loses a replica, not the
    # accounting invariant)
    for rep in router.replicas:
        e = rep.engine
        if rep.alive and (e._deferred_free or e.pool.pending_evict):
            e.pool.release(e._deferred_free)  # tpu-lint: disable=TPL213 -- post-run settlement: run() returned, no program in flight
            e._deferred_free = []
            e.pool.commit_evictable()
        acc = e.page_accounting()
        if acc["total"] != e.n_pages - 1:
            print(f"fleet_smoke: FAIL — engine {e.engine_id} ledger "
                  f"does not sum: {acc}", file=sys.stderr)
            return 1
        if rep.alive and (acc["slot_owned"] or acc["slot_shared"]
                          or acc["deferred_free"] or acc["in_flight"]):
            print(f"fleet_smoke: FAIL — survivor {e.engine_id} leaked "
                  f"pages: {acc}", file=sys.stderr)
            return 1

    st = router.stats
    print(f"fleet_smoke: OK — killed engine "
          f"{victim_engine.engine.engine_id} mid-decode, "
          f"{st['migrated_pages']} page(s) migrated "
          f"({st['migration_bytes']} bytes), {st['n_recovered']} "
          f"stream(s) resumed, all 5 streams (incl. sampled) "
          f"bit-identical to uninterrupted runs, survivor ledger "
          f"closes with no leak")
    return 0


if __name__ == "__main__":
    sys.exit(main())
