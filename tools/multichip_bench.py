"""Multichip bench — measurements behind bench.py's ``multichip_*`` keys.

Measures the hybrid-parallel (dp x pp x mp) train step against a serial
1-device run of the SAME config and global batch:

- ``step_ms``: best-of-3 two-step windows of the multichip step;
- ``tok_s_per_chip``: global tokens/s divided by device count;
- ``serial_step_ms``: the 1-device reference (scaling efficiency =
  serial / (n * multichip) — perfect linear scaling is 1.0);
- ``comm_ms``: isolated gradient-sync microbench (a full-parameter-sized
  fp32 psum over the dp axis, the dominant collective of the step) —
  comm_frac = comm_ms / step_ms is an isolated-phase ratio in the
  _bench_phases sense, not an additive partition (compute/comm overlap);
- ``quant_*``: the same step on a dp-only mesh with
  ``dist_allreduce_quant`` off vs on — int8-wire gradient-sync
  throughput plus the measured loss delta after identical step counts.

Mesh choice is deterministic per runtime: native partial-manual
shard_map runtimes get the full dp=2·pp=2·mp=2; jax_compat-shimmed ones
(where XLA CPU rejects the partial-manual pp lowering) get dp=4·pp=1·mp=2.

Standalone: ``python tools/multichip_bench.py`` prints one JSON line of
raw measurements. If the host has fewer than 2 devices it re-execs a
child with an 8-fake-device CPU world (XLA_FLAGS must precede jax init).
On-chip numbers come from bench.py calling ``measure()`` in-process.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_DEV = 8
_WINDOWS, _WIN_STEPS = 3, 2


def _mesh_shape(n: int, native: bool) -> tuple[int, int, int]:
    if native and n % 8 == 0:
        return (n // 4, 2, 2)
    if n % 2 == 0:
        return (n // 2, 1, 2)
    return (n, 1, 1)


def measure() -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.core import jax_compat
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.distributed.process_mesh import build_mesh
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.parallel import make_sharded_train_step

    n = len(jax.devices())
    assert n >= 2, f"multichip bench needs >= 2 devices, have {n}"
    native = "shard_map" not in jax_compat.PATCHED
    dp, pp, mp = _mesh_shape(n, native)
    n_micro = 2 if pp > 1 else 1

    cfg = GPTConfig(vocab_size=2048, hidden=128, n_layers=4, n_heads=4,
                    seq_len=64, dtype=jnp.float32)
    batch = 4 * dp * n_micro
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, size=(batch, cfg.seq_len))
    labs = rng.randint(0, cfg.vocab_size, size=(batch, cfg.seq_len))

    n_params = 0

    def run(mesh, n_microbatches, flag):
        """warm 1 step, then best-of-N windows; returns (ms/step, loss
        after the identical 1 + N*W step schedule — off/on deltas
        compare equal step counts)."""
        nonlocal n_params
        set_flags({"dist_allreduce_quant": flag})
        try:
            step, params, opt = make_sharded_train_step(
                cfg, mesh, n_microbatches=n_microbatches)
            n_params = sum(int(np.prod(x.shape))
                           for x in jax.tree.leaves(params))
            t = step.put_batch(toks)
            l = step.put_batch(labs)
            loss, params, opt = step(params, opt, t, l)
            float(loss)  # fetch = the reliable device sync (bench.py note)
            best = float("inf")
            for _ in range(_WINDOWS):
                t0 = time.perf_counter()
                for _ in range(_WIN_STEPS):
                    loss, params, opt = step(params, opt, t, l)
                lf = float(loss)
                best = min(best, (time.perf_counter() - t0) / _WIN_STEPS)
            return best * 1000.0, lf
        finally:
            set_flags({"dist_allreduce_quant": False})

    mesh = build_mesh((dp, pp, mp), ("dp", "pp", "mp"))
    step_ms, _ = run(mesh, n_micro, False)

    serial_mesh = build_mesh((1, 1, 1), ("dp", "pp", "mp"),
                             devices=[jax.devices()[0]])
    serial_ms, _ = run(serial_mesh, 1, False)

    # isolated gradient-sync microbench: full-parameter fp32 psum over dp
    dmesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    g = jnp.zeros((n, n_params), jnp.float32)

    def body(x):
        return jax.lax.psum(x[0], "dp")[None]

    sync = jax.shard_map(body, in_specs=P("dp"), out_specs=P("dp"),
                         axis_names={"dp"}, check_vma=False)
    with jax.sharding.set_mesh(dmesh):
        jf = jax.jit(sync)
        jax.block_until_ready(jf(g))
        comm_best = float("inf")
        for _ in range(_WINDOWS):
            t0 = time.perf_counter()
            jax.block_until_ready(jf(g))
            comm_best = min(comm_best, time.perf_counter() - t0)

    # quantized gradient sync: dp-only mesh, off vs on, equal step counts
    qmesh = build_mesh((n, 1, 1), ("dp", "pp", "mp"))
    qoff_ms, qoff_loss = run(qmesh, 1, False)
    qon_ms, qon_loss = run(qmesh, 1, True)
    qbatch = 4 * n

    return {
        "mesh": f"dp{dp}xpp{pp}xmp{mp}",
        "n_devices": n,
        "step_ms": round(step_ms, 3),
        "tok_s_per_chip": round(batch * cfg.seq_len / (step_ms / 1e3) / n, 1),
        "serial_step_ms": round(serial_ms, 3),
        "comm_ms": round(comm_best * 1000.0, 3),
        "quant_tok_s": round(qbatch * cfg.seq_len / (qon_ms / 1e3), 1),
        "quant_off_tok_s": round(qbatch * cfg.seq_len / (qoff_ms / 1e3), 1),
        "quant_off_loss": qoff_loss,
        "quant_on_loss": qon_loss,
    }


def main(argv=None) -> int:
    import jax

    if len(jax.devices()) >= 2:
        print(json.dumps(measure()), flush=True)
        return 0

    # 1-device host (CPU CI): re-exec with an 8-fake-device world — the
    # flag must be in the environment before the child's jax initializes
    env = dict(os.environ)
    extra = f"--xla_force_host_platform_device_count={N_DEV}"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + extra).strip()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, cwd=_REPO, timeout=1800)
    return proc.returncode


if __name__ == "__main__":
    sys.path.insert(0, _REPO)
    sys.exit(main())
