"""Multichip smoke — ci_check.sh gate "multichip" (exit 80).

Three contracts on an 8-fake-device CPU world
(``--xla_force_host_platform_device_count``):

1. **dryrun**: the full hybrid-parallel train step compiles and runs with
   serial-parity loss AND a clean SPMD log — any "Involuntary full
   rematerialization" line is a hard failure (__graft_entry__ pin,
   MULTICHIP_r05 regression). Native partial-manual runtimes run the full
   dp=2·pp=2·mp=2 mesh; on a jax_compat-shimmed runtime (0.4.x, where XLA
   CPU rejects the partial-manual PartitionId lowering) it downgrades to
   dp=4·pp=1·mp=2 and says so — the driver environment runs the real
   thing.
2. **quant**: a 2-step quantized-collective run on a dp=8 mesh:
   ``dist_allreduce_quant=0`` is bit-identical across independent builds,
   ``=1`` tracks the fp32 loss within the parity bound.

Usage: ``python tools/multichip_smoke.py [--part all|dryrun|quant]``.
The parent process self-provisions the 8-device world in a subprocess
(XLA_FLAGS must be set before jax initializes).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_DEV = 8
QUANT_REL_BOUND = 5e-3


def _native_partial_manual() -> bool:
    from paddle_tpu.core import jax_compat

    return "shard_map" not in jax_compat.PATCHED


def _part_dryrun() -> None:
    import __graft_entry__ as g

    if _native_partial_manual():
        shape = None          # _factor_mesh(8) -> the full (2, 2, 2)
    else:
        shape = (4, 1, 2)
        print("multichip_smoke: shimmed shard_map runtime — downgrading "
              "dryrun mesh to dp=4 pp=1 mp=2 (partial-manual pp is not "
              "lowerable on XLA CPU here)", flush=True)
    g._dryrun_impl(N_DEV, shape=shape)


def _part_quant() -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.parallel.train_step import make_sharded_train_step

    mesh = Mesh(np.array(jax.devices()[:N_DEV]).reshape(N_DEV, 1, 1),
                ("dp", "pp", "mp"))
    cfg = GPTConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=2,
                    seq_len=16, dtype=jnp.float32)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (16, cfg.seq_len)).astype(np.int32)
    lab = np.roll(tok, -1, axis=1)

    def losses(flag: bool, steps: int = 2):
        set_flags({"dist_allreduce_quant": flag})
        try:
            step, params, opt = make_sharded_train_step(cfg, mesh)
            out = []
            for _ in range(steps):
                loss, params, opt = step(params, opt, tok, lab)
                out.append(float(loss))
        finally:
            set_flags({"dist_allreduce_quant": False})
        return out

    off1, off2, on = losses(False), losses(False), losses(True)
    assert off1 == off2, \
        f"dist_allreduce_quant=0 not bit-identical: {off1} vs {off2}"
    rels = [abs(q - r) / max(abs(r), 1e-9) for q, r in zip(on, off1)]
    assert all(r < QUANT_REL_BOUND for r in rels), \
        f"quant-sync loss off parity bound: off={off1} on={on} rels={rels}"
    print(f"multichip_smoke quant OK: off={off1[-1]:.4f} on={on[-1]:.4f} "
          f"max_rel={max(rels):.1e}", flush=True)


def _child(part: str) -> None:
    if part in ("all", "dryrun"):
        _part_dryrun()
    if part in ("all", "quant"):
        _part_quant()
    print(f"multichip_smoke OK part={part}", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--part", choices=("all", "dryrun", "quant"),
                    default="all")
    ap.add_argument("--_child", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args._child:
        sys.path.insert(0, _REPO)
        _child(args.part)
        return 0

    env = dict(os.environ)
    extra = f"--xla_force_host_platform_device_count={N_DEV}"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + extra).strip()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--part", args.part,
         "--_child"],
        env=env, cwd=_REPO, timeout=1800)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
